"""Per-round JSON round reports.

One structured JSON object per completed round, appended to a JSONL file:
round id, per-phase durations, message accepted/rejected/discarded counts
per phase, unique-mask total, aggregation kernel stats (calls, device-synced
seconds, elements, derived elements/sec) and any phase events. Consumers
(``tools/tpu_watch.py``, ``bench.py``, dashboards) read one artifact instead
of scraping coordinator logs.

Fed by ``telemetry.bridge.BridgedMetrics``: a report window opens when Idle
records ``round_total`` for a new round and flushes when the next round
starts (or on ``close()`` for the in-flight tail).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Optional

from . import profiling

logger = logging.getLogger("xaynet.telemetry")

# mask-kernel auto-calibration verdicts since the last report flush
# (ops.masking_jax records; the round report drains). Module-level like the
# profiling round window: verdicts are process-wide facts, and attributing
# them to the round whose report drains them is exactly the audit trail the
# headline needs (a verdict flip shows up in THAT round's report).
_calib_lock = threading.Lock()
_mask_calibrations: list[dict] = []

# bound: verdicts are one-per-(backend, shape, mesh) and memoized, so a
# handful per process is normal; a runaway recording bug must not grow the
# report without limit
_MAX_CALIBRATIONS = 64


def record_mask_calibration(entry: dict) -> None:
    """Record one auto-calibration verdict (winner + per-candidate probe
    walls) for the next round report."""
    with _calib_lock:
        if len(_mask_calibrations) < _MAX_CALIBRATIONS:
            _mask_calibrations.append(dict(entry))


def drain_mask_calibrations() -> list[dict]:
    with _calib_lock:
        out, _mask_calibrations[:] = list(_mask_calibrations), []
    return out


def _streaming_snapshot() -> Optional[dict]:
    """Streaming-fold pipeline state for the round report, read from the
    registry gauges (None when no streaming pipeline ever ran — host-mode
    coordinators don't grow an empty section): the pipeline overlap ratio,
    degraded flag, and — for shard-parallel folds — the per-shard overlap
    ratios keyed by shard index."""
    from .registry import get_registry

    reg = get_registry()
    overlap = reg.sample_value("xaynet_streaming_overlap_ratio")
    if overlap is None:
        return None
    out = {
        "overlap_ratio": round(overlap, 4),
        "degraded": bool(reg.sample_value("xaynet_streaming_degraded") or 0),
    }
    family = reg.get("xaynet_streaming_shard_overlap_ratio")
    if family is not None:
        shards = {
            key[0]: round(child.value, 4) for key, child in family.children()
        }
        if shards:
            out["shard_overlap_ratio"] = shards
    return out


def _bytes_counters() -> dict[str, dict[str, float]]:
    """Cumulative staged/reduced byte counters from the registry, keyed
    ``{series: {label_value: total}}`` (packed-reduction observability,
    docs/DESIGN.md §17)."""
    from .registry import get_registry

    reg = get_registry()
    out: dict[str, dict[str, float]] = {}
    for name, short in (
        ("xaynet_bytes_staged_total", "staged"),
        ("xaynet_bytes_reduced_total", "reduced"),
    ):
        family = reg.get(name)
        if family is None:
            continue
        series = {key[0]: child.value for key, child in family.children()}
        if series:
            out[short] = series
    return out


def _timeline_snapshot(tenant: str, round_id: Optional[int]) -> Optional[dict]:
    """The round-wall decomposition for the flushing round from the
    always-on timeline fold (docs/DESIGN.md §20); ``None`` when tracing is
    off or the round left no foldable bracket. The report carries tenant
    and round id already, so both are stripped from the section."""
    if round_id is None:
        return None
    from .timeline import get_timeline

    decomp = get_timeline().fold_for_report(tenant, round_id)
    if decomp is None:
        return None
    out = dict(decomp)
    out.pop("round_id", None)
    out.pop("tenant", None)
    return out


def _fairness_snapshot() -> Optional[dict]:
    """Per-tenant fold-batch grants since the previous round flush, read
    from the tenant scheduler (lazy import: telemetry must not pull the
    tenancy machinery into processes that never aggregate). ``None`` until
    the scheduler has granted slots to MORE than one tenant — single-tenant
    reports don't grow a trivial section."""
    from ..tenancy.scheduler import get_scheduler

    split = get_scheduler().split()
    # cumulative (not a drained window): each tenant's reporter flushes on
    # its own round cadence, and a shared drained delta would let one
    # tenant's flush steal another's window; consumers diff consecutive
    # reports for per-round rates
    return split if len(split) >= 2 else None


class RoundReporter:
    """Accumulates one round's telemetry and writes it as a JSON line."""

    def __init__(self, path: Optional[str] = None, tenant: str = "default"):
        self.path = path
        # the tenant this reporter's rounds belong to: stamped on every
        # report line so N tenants can share one JSONL file (§19)
        self.tenant = tenant
        self.last_report: Optional[dict] = None
        self._lock = threading.Lock()
        self._round_id: Optional[int] = None
        self._started: float = 0.0
        # previous cumulative byte-counter sample: the report carries
        # per-round DELTAS (bytes moved during this round), not process
        # totals
        self._bytes_prev: dict[str, dict[str, float]] = {}
        self._reset()

    def _reset(self) -> None:
        self._phases: list[str] = []
        self._durations: dict[str, float] = {}
        self._messages: dict[str, dict[str, int]] = {}
        self._masks_total: Optional[int] = None
        self._events: list[dict] = []

    # --- recording (called by the bridge) ---------------------------------

    def begin_round(self, round_id: int) -> None:
        with self._lock:
            if self._round_id is not None and self._round_id != round_id:
                self._flush_locked()
            if self._round_id != round_id:
                self._round_id = round_id
                self._started = time.time()

    def record_phase(self, phase: str) -> None:
        with self._lock:
            self._phases.append(phase)

    def record_phase_duration(self, phase: str, seconds: float) -> None:
        with self._lock:
            self._durations[phase] = round(
                self._durations.get(phase, 0.0) + seconds, 6
            )

    def record_message(self, phase: str, outcome: str) -> None:
        with self._lock:
            counts = self._messages.setdefault(
                phase, {"accepted": 0, "rejected": 0, "discarded": 0}
            )
            counts[outcome] = counts.get(outcome, 0) + 1

    def record_masks_total(self, count: int) -> None:
        with self._lock:
            self._masks_total = count

    def record_event(self, kind: str, detail: str) -> None:
        with self._lock:
            self._events.append({"kind": kind, "detail": detail})

    # --- flushing ----------------------------------------------------------

    def _flush_locked(self) -> None:
        if self._round_id is None:
            return
        report = {
            "ts": round(time.time(), 3),
            "round_id": self._round_id,
            "tenant": self.tenant,
            "seconds": round(time.time() - self._started, 3),
            "phases": self._phases,
            "phase_durations": dict(self._durations),
            "messages": self._messages,
            "masks_total": self._masks_total,
            "kernels": profiling.drain_round_stats(),
            "events": self._events,
        }
        timeline_section = _timeline_snapshot(self.tenant, self._round_id)
        if timeline_section is not None:
            # the round-wall decomposition from the always-on timeline
            # fold (docs/DESIGN.md §20): end-to-end wall, per-phase
            # wall/self time, cross-phase overlap + gap (the identity
            # sum(phase walls) - overlap + gap == wall holds), top-k
            # slowest spans and the degraded flag
            report["round_wall"] = timeline_section
        fairness = _fairness_snapshot()
        if fairness is not None:
            # the tenant scheduler's fold-batch split since the last round
            # flush: how this round's device work interleaved across
            # tenants (docs/DESIGN.md §19). Only present once the
            # scheduler has actually granted multi-tenant slots.
            report["fairness"] = fairness
        streaming = _streaming_snapshot()
        if streaming is not None:
            report["streaming"] = streaming
        current = _bytes_counters()
        deltas = {
            short: {
                label: int(total - self._bytes_prev.get(short, {}).get(label, 0.0))
                for label, total in series.items()
                if total - self._bytes_prev.get(short, {}).get(label, 0.0) > 0
            }
            for short, series in current.items()
        }
        deltas = {k: v for k, v in deltas.items() if v}
        if deltas:
            # bytes moved THIS round on the staging (packed/unpacked/wire
            # layouts) and cross-shard combine (scatter/gather) paths —
            # the per-round view of the packed-reduction counters (§17)
            report["bytes"] = deltas
        self._bytes_prev = current
        from .timeline import drain_overlap_window

        overlap = drain_overlap_window()
        if overlap:
            # phase-overlap work that landed during this round
            # (docs/DESIGN.md §22): hidden seconds by kind (spec_derive |
            # drain | eager_unmask) with the speculation reconciliation
            # counts — the round-report view of why the round wall came in
            # under the serial sum of phase walls
            report["overlap"] = overlap
        calibrations = drain_mask_calibrations()
        if calibrations:
            # auto-calibration verdicts that landed during this round:
            # winner + per-candidate probe walls per (backend, length,
            # bucket, mesh) — a headline shift caused by a verdict flip is
            # auditable from the report instead of requiring a re-run
            report["mask_calibration"] = calibrations
        from .tracing import get_tracer

        ctx = get_tracer().round_ctx()
        if ctx is not None:
            # join key to the per-round Chrome trace / flight dumps
            report["trace_id"] = ctx.trace_id
        self.last_report = report
        if self.path:
            # a bad report path must never take the coordinator down: the
            # flush runs inside round_total (next round's Idle) and inside
            # close() — raising would abort the round / skip the sink drain
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(report) + "\n")
            except OSError as err:
                logger.warning("round report write failed (%s): %s", self.path, err)
        self._round_id = None
        self._reset()

    def flush(self) -> None:
        """Write the in-flight round (shutdown path)."""
        with self._lock:
            self._flush_locked()
