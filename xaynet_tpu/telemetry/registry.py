"""Prometheus-style in-process metrics registry (stdlib only).

The coordinator's single source of truth for operational numbers: counters,
gauges and histograms with labels, rendered in the Prometheus text
exposition format (v0.0.4) by ``GET /metrics`` on the REST server. The
reference ships its measurements straight to InfluxDB
(rust/xaynet-server/src/metrics/); here every measurement lands in this
registry first and the Influx/Jsonl sinks consume it through
``telemetry.bridge`` — one registry, many consumers, no new dependencies.

Concurrency: metric children carry their own lock, so the asyncio loop, the
message-pipeline thread pool and the metrics dispatcher thread can all
record without coordination. Family creation is idempotent — asking for an
existing (name, kind, labelnames) returns the same family, so modules can
declare their metrics at import time against the process registry.
"""

from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from typing import Iterable, Optional, Sequence

# Prometheus' defaults stop at 10s; phases can legitimately take minutes
# (time.max windows), so the tail extends to the reference's 600s ceiling.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)


class MetricError(ValueError):
    """Invalid metric declaration or use (type conflict, bad label set, ...)."""


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if value != value:  # NaN
        return "NaN"
    as_float = float(value)
    if as_float.is_integer() and abs(as_float) < 1e15:
        return str(int(as_float))
    return repr(as_float)


def _label_string(labelnames: Sequence[str], labelvalues: Sequence[str], extra: str = "") -> str:
    pairs = [
        f'{name}="{_escape_label_value(value)}"'
        for name, value in zip(labelnames, labelvalues)
    ]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Counter:
    """Monotonically increasing value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def _render(self, lines: list[str], name: str, labelstr: str) -> None:
        lines.append(f"{name}{labelstr} {_format_value(self._value)}")


class _Gauge:
    """Value that can go up and down."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value

    def _render(self, lines: list[str], name: str, labelstr: str) -> None:
        lines.append(f"{name}{labelstr} {_format_value(self._value)}")


class _Histogram:
    """Cumulative-bucket histogram with ``_sum`` and ``_count`` series."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float]):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise MetricError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot is +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, bound in enumerate(self._bounds):
                if value <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @contextmanager
    def time(self):
        """Observe the wall time of the enclosed block."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count

    def bucket_counts(self) -> dict[float, int]:
        """Cumulative count per upper bound (``inf`` key == total count)."""
        out, running = {}, 0
        with self._lock:
            for bound, n in zip(self._bounds + (math.inf,), self._counts):
                running += n
                out[bound] = running
        return out

    def _render(self, lines: list[str], name: str, labelstr: str) -> None:
        with self._lock:
            counts = list(self._counts)
            total, sum_ = self._count, self._sum
        # labelstr is "{a=\"b\"}" or ""; splice le into the existing braces
        base = labelstr[1:-1] if labelstr else ""
        running = 0
        for bound, n in zip(self._bounds + (math.inf,), counts):
            running += n
            le = f'le="{_format_value(bound) if bound != math.inf else "+Inf"}"'
            joined = f"{base},{le}" if base else le
            lines.append(f"{name}_bucket{{{joined}}} {running}")
        lines.append(f"{name}_sum{labelstr} {_format_value(sum_)}")
        lines.append(f"{name}_count{labelstr} {total}")


_KINDS = {"counter": _Counter, "gauge": _Gauge, "histogram": _Histogram}


class MetricFamily:
    """One named metric with a fixed label schema and per-labelset children.

    A family with no labels proxies the child API (``inc``/``set``/
    ``observe``/...) directly, so ``registry.counter("x").inc()`` works.
    """

    def __init__(self, name: str, kind: str, help: str, labelnames: Sequence[str], **child_kwargs):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._child_kwargs = child_kwargs
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = _KINDS[kind](**child_kwargs)

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise MetricError(
                f"{self.name}: expected labels {self.labelnames}, got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _KINDS[self.kind](**self._child_kwargs)
        return child

    def _default_child(self):
        if self.labelnames:
            raise MetricError(f"{self.name} has labels {self.labelnames}; use .labels(...)")
        return self._children[()]

    def children(self) -> list[tuple[tuple[str, ...], object]]:
        """Snapshot of the labeled children as ``(labelvalues, child)``
        pairs, sorted by label values — the public enumeration surface for
        readers that aggregate a family (/healthz sections, round
        reports), so they never touch the internal storage layout."""
        with self._lock:
            return sorted(self._children.items())

    # unlabeled convenience proxies ----------------------------------------

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def time(self):
        return self._default_child().time()

    @property
    def value(self):
        return self._default_child().value

    @property
    def sum(self):
        return self._default_child().sum

    @property
    def count(self):
        return self._default_child().count

    def bucket_counts(self):
        return self._default_child().bucket_counts()

    # exposition ------------------------------------------------------------

    def render(self, lines: list[str]) -> None:
        lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            children = sorted(self._children.items())
        for labelvalues, child in children:
            child._render(lines, self.name, _label_string(self.labelnames, labelvalues))


class MetricsRegistry:
    """Thread-safe collection of metric families with text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, MetricFamily] = {}

    def _family(self, name: str, kind: str, help: str, labelnames, **kwargs) -> MetricFamily:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if (
                    existing.kind != kind
                    or existing.labelnames != labelnames
                    or existing._child_kwargs != kwargs
                ):
                    raise MetricError(
                        f"metric {name} already registered as {existing.kind}"
                        f"{existing.labelnames} {existing._child_kwargs}, "
                        f"requested {kind}{labelnames} {kwargs}"
                    )
                return existing
            family = MetricFamily(name, kind, help, labelnames, **kwargs)
            self._families[name] = family
            return family

    def counter(self, name: str, help: str = "", labelnames: Iterable[str] = ()) -> MetricFamily:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Iterable[str] = ()) -> MetricFamily:
        return self._family(name, "gauge", help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Iterable[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> MetricFamily:
        return self._family(name, "histogram", help, labelnames, buckets=buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def sample_value(self, name: str, labels: Optional[dict] = None):
        """Current value of one counter/gauge child, or ``None`` if absent
        (test/report convenience; histograms expose ``sum``/``count`` on the
        child instead)."""
        family = self.get(name)
        if family is None:
            return None
        key = tuple(str((labels or {}).get(n, "")) for n in family.labelnames)
        child = family._children.get(key)
        return None if child is None else child.value

    def render(self) -> str:
        """The full registry in Prometheus text exposition format."""
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        lines: list[str] = []
        for family in families:
            family.render(lines)
        return "\n".join(lines) + "\n" if lines else ""


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem records into by default."""
    return _default_registry
