"""Always-on round-wall timeline: a streaming critical-path fold over the
per-round span buffer (docs/DESIGN.md §20).

Five perf PRs optimized throughput *inside* phases; the number a production
operator actually watches — end-to-end round wall — was still only
recoverable offline from a Chrome-trace export. This module makes it a
first-class in-process signal: every time the tracer flushes a round window
(``Tracer.add_flush_hook``), one O(n) pass over the round's spans computes

- the **round wall** — Idle-close → Unmask-complete, i.e. the interval from
  the end of the ``phase.idle`` span (the moment the new round's params are
  live) to the end of the ``phase.unmask`` span (the moment the global
  model is published). Falls back to the root ``round`` span's duration
  when a failed round never reached unmask;
- a **per-phase decomposition** — per-phase wall and *self time* (the part
  of the phase's interval no other phase overlaps), the cross-phase
  **overlap** and the uncovered **gap**, chosen so the identity
  ``sum(phase walls) - overlap + gap == wall`` holds exactly: the report's
  numbers always sum (with overlap accounted) to the recorded wall;
- the **top-k slowest spans** of the round — "where did this round's wall
  go" without opening a trace viewer;
- the round's **degraded flag** — any phase span that closed its request
  window ``degraded``/``timeout`` (the outcome rides in the span attrs).

The wall lands in the ``xaynet_round_wall_seconds{tenant}`` histogram, the
decomposition in the round report (``telemetry.report``) and on the
``/statusz`` operator console, and every completed round is forwarded to
the SLO engine (``telemetry.slo``). The fold is always on — it costs one
list pass per round (bounded by the span-buffer cap, measured well under
0.1% of a round's aggregation wall by ``tools/trace_overhead.py``) — and,
like every telemetry consumer, it is fail-soft: the tracer swallows flush-
hook exceptions, so a fold bug can never fail a round.
"""

from __future__ import annotations

import heapq
import threading
from collections import deque
from typing import Optional

from .registry import get_registry
from .tracing import get_tracer

# log ladder covering 0.05s-120s: the registry default tops out sparsely
# above 30s, so a large-model round (61s @25M) landed in a coarse tail
# bucket and burn-rate math saw almost no distribution. Sub-50ms rounds
# only exist in unit tests; >120s rounds are SLO pages, +Inf is fine.
ROUND_WALL_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 15.0, 30.0, 60.0, 90.0, 120.0,
)

ROUND_WALL = get_registry().histogram(
    "xaynet_round_wall_seconds",
    "End-to-end round wall (Idle-close to Unmask-complete), by tenant — "
    "the operator headline the SLO engine budgets (docs/DESIGN.md §20).",
    ("tenant",),
    buckets=ROUND_WALL_BUCKETS,
)

OVERLAP_SECONDS = get_registry().counter(
    "xaynet_overlap_seconds_total",
    "Seconds of cross-phase work hidden inside another phase's wall, by "
    "overlap kind (spec_derive | eager_unmask | drain; docs/DESIGN.md §22).",
    ("kind",),
)
SPEC_DERIVE = get_registry().counter(
    "xaynet_spec_derive_total",
    "Speculatively derived sum2 mask seeds by outcome: hit (speculated and "
    "folded), miss (derived on demand at sum2), discard (mis-speculated, "
    "subtracted back out; docs/DESIGN.md §22).",
    ("outcome",),
)

# per-round overlap window: entries recorded by the overlap features and
# drained into the round report's `overlap` section (the
# `record_mask_calibration` idiom — bounded, fail-soft)
_overlap_window_lock = threading.Lock()
_overlap_window: list[dict] = []
_MAX_OVERLAP_ENTRIES = 256


def record_overlap(kind: str, seconds: float, tenant: str = "default", **extra) -> None:
    """Credit ``seconds`` of work hidden under another phase's wall and
    stash one entry for the round report's ``overlap`` section."""
    OVERLAP_SECONDS.labels(kind=kind).inc(max(0.0, seconds))
    entry = {"kind": kind, "seconds": round(seconds, 6), "tenant": tenant, **extra}
    with _overlap_window_lock:
        if len(_overlap_window) < _MAX_OVERLAP_ENTRIES:
            _overlap_window.append(entry)


def record_spec_outcomes(hits: int = 0, misses: int = 0, discards: int = 0) -> None:
    """Count speculative-derive seed outcomes (hit | miss | discard)."""
    if hits:
        SPEC_DERIVE.labels(outcome="hit").inc(hits)
    if misses:
        SPEC_DERIVE.labels(outcome="miss").inc(misses)
    if discards:
        SPEC_DERIVE.labels(outcome="discard").inc(discards)


def drain_overlap_window() -> list[dict]:
    """Drain the per-round overlap entries (round-report flush)."""
    global _overlap_window
    with _overlap_window_lock:
        out, _overlap_window = _overlap_window, []
    return out

# phases inside the round-wall bracket (idle is the bracket's left edge,
# not part of the decomposition; failure/shutdown abort the bracket)
_WORK_PHASES = ("sum", "update", "sum2", "unmask")
_TOP_K = 5
# recent walls kept per tenant for the /statusz sparkline
_SPARK_WINDOW = 64


def _merge(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted, disjoint union of (start, end) intervals."""
    merged: list[tuple[float, float]] = []
    for start, end in sorted(intervals):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def _measure(merged: list[tuple[float, float]]) -> float:
    return sum(end - start for start, end in merged)


def _intersection(
    a: list[tuple[float, float]], b: list[tuple[float, float]]
) -> float:
    """Measure of the intersection of two disjoint-sorted interval lists."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if lo < hi:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def fold_spans(round_id: int, spans: list) -> Optional[dict]:
    """One streaming pass over a round's span buffer -> the round-wall
    decomposition dict (None when the buffer carries no usable bracket).

    The pass collects the phase spans' intervals, the root span, the
    degraded flag and a bounded top-k heap in a single iteration; the
    interval arithmetic afterwards touches only the handful of phase
    intervals, so the cost is O(n) in the buffer size with a tiny constant
    — cheap enough to stay always-on.
    """
    phase_iv: dict[str, list[tuple[float, float]]] = {}
    idle_end: Optional[float] = None
    root = None
    tenant = ""
    degraded = False
    heap: list[tuple[float, int, str]] = []  # (duration, seq, name) min-heap
    for seq, span in enumerate(spans):
        name = span.name
        if name == "round":
            root = span
            continue
        # the top-k heap sees every non-root span except idle (which is
        # outside the wall bracket), phases included: a phase dominating
        # its own children IS the signal (self time)
        if name != "phase.idle":
            if len(heap) < _TOP_K:
                heapq.heappush(heap, (span.duration, seq, name))
            elif span.duration > heap[0][0]:
                heapq.heapreplace(heap, (span.duration, seq, name))
        if name.startswith("overlap."):
            # an overlap span is WORK BELONGING TO ITS HOME PHASE (the
            # `phase` attr) that ran outside the phase's own span — a
            # speculative derive inside update, update's drain riding the
            # sum2 window, an eager per-shard unmask inside the drain.
            # Merging it into the home phase's interval set makes the
            # identity's overlap term measure the hidden work: phase
            # intervals now genuinely intersect, so ``sum(phase walls) -
            # overlap + gap == wall`` reports negative slack (wall < sum
            # of walls) exactly when the overlap engine saved wall time.
            home = str(span.attrs.get("phase") or "")
            if home in _WORK_PHASES and span.duration > 0:
                phase_iv.setdefault(home, []).append(
                    (span.start, span.start + span.duration)
                )
            continue
        if not name.startswith("phase."):
            continue
        phase = name[len("phase."):]
        outcome = span.attrs.get("outcome")
        if outcome in ("degraded", "timeout"):
            degraded = True
        if span.attrs.get("tenant"):
            tenant = str(span.attrs["tenant"])
        end = span.start + span.duration
        if phase == "idle":
            idle_end = end if idle_end is None else max(idle_end, end)
        elif phase in _WORK_PHASES:
            phase_iv.setdefault(phase, []).append((span.start, end))
    if root is None and not phase_iv:
        return None
    merged = {p: _merge(iv) for p, iv in phase_iv.items()}
    # bracket: Idle-close -> Unmask-complete; a round that died before
    # unmask (or a buffer that lost idle to the cap) falls back to the
    # edges the buffer still has, and an empty decomposition falls back to
    # the root span outright
    ends = [iv[-1][1] for iv in merged.values()]
    starts = [iv[0][0] for iv in merged.values()]
    if idle_end is not None:
        left = idle_end
    elif starts:
        left = min(starts)
    else:
        left = root.start
    right_candidates = merged.get("unmask")
    if right_candidates:
        right = right_candidates[-1][1]
    elif ends:
        right = max(ends)
    else:
        right = root.start + root.duration
    wall = max(0.0, right - left)
    # clip each phase to the bracket so the identity below is exact even
    # when a phase span straddles an edge (idle overlap-starting sum, say)
    clipped = {
        p: [(max(s, left), min(e, right)) for s, e in iv if min(e, right) > max(s, left)]
        for p, iv in merged.items()
    }
    clipped = {p: iv for p, iv in clipped.items() if iv}
    union = _merge([pair for iv in clipped.values() for pair in iv])
    union_s = _measure(union)
    phases: dict[str, dict[str, float]] = {}
    total_phase_wall = 0.0
    for p in _WORK_PHASES:
        iv = clipped.get(p)
        if not iv:
            continue
        p_wall = _measure(iv)
        others = _merge(
            [pair for q, oiv in clipped.items() if q != p for pair in oiv]
        )
        phases[p] = {
            "wall_s": round(p_wall, 6),
            "self_s": round(p_wall - _intersection(iv, others), 6),
        }
        total_phase_wall += p_wall
    overlap = max(0.0, total_phase_wall - union_s)
    gap = max(0.0, wall - union_s)
    slowest = [
        {"span": name, "seconds": round(dur, 6)}
        for dur, _, name in sorted(heap, key=lambda t: -t[0])
    ]
    out = {
        "round_id": round_id,
        "tenant": tenant or "default",
        "wall_s": round(wall, 6),
        "phases": phases,
        "overlap_s": round(overlap, 6),
        "gap_s": round(gap, 6),
        "overlap_ratio": round(overlap / wall, 4) if wall > 0 else 0.0,
        "degraded": degraded,
        "spans": len(spans),
        "slowest": slowest,
    }
    return out


# per-tenant span accumulator bound: a tenant whose round never reaches
# unmask (crash-looping Failure) must not grow memory without limit
_PENDING_CAP = 2048


def _span_tenant(span) -> Optional[str]:
    tenant = span.attrs.get("tenant")
    return str(tenant) if tenant else None


class RoundTimeline:
    """Per-process timeline state: last decomposition + recent walls per
    tenant (one instance behind :func:`get_timeline`, registered as a
    tracer flush hook at import).

    Multi-tenant coordinators share ONE tracer, so a flushed round window
    may interleave several tenants' spans and a tenant's round may span
    several windows (every tenant's Idle flushes the shared window). The
    timeline therefore accumulates phase spans PER TENANT across flushes
    and folds a tenant's round the moment its ``phase.unmask`` span
    arrives — per-tenant walls stay exact even under interleaving.
    Untagged spans (streaming/request children carry no tenant attr) ride
    into the top-k only when a window belongs to a single tenant.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._last: dict[str, dict] = {}  # guarded-by: _lock
        self._walls: dict[str, deque] = {}  # guarded-by: _lock
        self._pending: dict[str, list] = {}  # guarded-by: _lock
        self._rounds = 0  # guarded-by: _lock

    @staticmethod
    def _partition(spans: list) -> dict[str, list]:
        """Group a span buffer by tenant: phase spans carry the tenant
        attr; untagged spans are attributed only when exactly one tenant
        owns the buffer."""
        by_tenant: dict[str, list] = {}
        untagged: list = []
        for seq, span in enumerate(spans):
            if span.name == "round":
                continue
            tenant = _span_tenant(span)
            if tenant is not None:
                by_tenant.setdefault(tenant, []).append((seq, span))
            else:
                untagged.append((seq, span))
        if len(by_tenant) == 1 and untagged:
            # merge in BUFFER order: the fold splits a tenant's list at its
            # unmask span, so an untagged child appended at the end would
            # leak into the next round's window instead of this fold
            only = next(iter(by_tenant))
            by_tenant[only] = sorted(
                by_tenant[only] + untagged, key=lambda pair: pair[0]
            )
        return {t: [span for _, span in lst] for t, lst in by_tenant.items()}

    # -- fold consumer (tracer flush hook) ----------------------------------

    def on_round(self, round_id: int, spans: list) -> None:
        by_tenant = self._partition(spans)
        if not by_tenant:
            # no phase spans at all (edge/SDK processes, span-less tests):
            # the root span's duration is still a round wall
            decomp = fold_spans(round_id, spans)
            if decomp is not None:
                self._finalize(decomp)
            return
        for tenant, tenant_spans in by_tenant.items():
            with self._lock:
                merged = self._pending.pop(tenant, []) + tenant_spans
            unmask_at = None
            for i, span in enumerate(merged):
                if span.name == "phase.unmask":
                    unmask_at = i
            if unmask_at is None:
                with self._lock:
                    self._pending[tenant] = merged[-_PENDING_CAP:]
                continue
            # spans recorded after unmask (the next round's idle, say)
            # seed the next accumulation window instead of polluting the
            # completed round's bracket
            fold_part, rest = merged[: unmask_at + 1], merged[unmask_at + 1:]
            rid = merged[unmask_at].attrs.get("round_id", round_id)
            decomp = fold_spans(rid, fold_part)
            with self._lock:
                if rest:
                    self._pending[tenant] = rest[-_PENDING_CAP:]
            if decomp is not None:
                decomp["tenant"] = tenant
                self._finalize(decomp)

    def _finalize(self, decomp: dict) -> None:
        tenant = decomp["tenant"]
        ROUND_WALL.labels(tenant=tenant).observe(decomp["wall_s"])
        with self._lock:
            self._last[tenant] = decomp
            self._walls.setdefault(tenant, deque(maxlen=_SPARK_WINDOW)).append(
                (decomp["round_id"], decomp["wall_s"])
            )
            self._rounds += 1
        # feed the SLO engine (lazy import: slo imports nothing from here,
        # but keeping the edge one-directional at import time is cheaper
        # than reasoning about cycles)
        from . import slo

        slo.get_engine().on_round(
            tenant, decomp["round_id"], decomp["wall_s"], decomp["degraded"]
        )

    # -- readers (round report, /statusz console, tests) --------------------

    def fold_for_report(self, tenant: str, round_id: int) -> Optional[dict]:
        """The decomposition for ``(tenant, round_id)`` AT REPORT-FLUSH
        TIME: the report flushes (next round's Idle ``__init__``) before
        the tracer window closes (next round's Idle ``process``), so the
        completed round's spans usually still sit in the open window —
        fold the pending accumulator plus a snapshot of the open buffer;
        fall back to the last flushed decomposition (multi-tenant windows
        flush on every tenant's round boundary, so the fold often already
        ran)."""
        open_id, open_spans = get_tracer().round_spans_snapshot()
        with self._lock:
            merged = list(self._pending.get(tenant, ()))
        if open_id is not None and open_spans:
            merged += self._partition(open_spans).get(tenant, [])
        if any(s.name == "phase.unmask" for s in merged):
            decomp = fold_spans(round_id, merged)
            if decomp is not None:
                decomp["tenant"] = tenant
                return decomp
        last = self.last(tenant)
        if last is not None and last.get("round_id") == round_id:
            return last
        return None

    def last(self, tenant: str = "default") -> Optional[dict]:
        """The most recent folded round's decomposition for ``tenant``."""
        with self._lock:
            decomp = self._last.get(tenant)
            return dict(decomp) if decomp is not None else None

    def recent_walls(self, tenant: str = "default") -> list[tuple[int, float]]:
        """Recent ``(round_id, wall_s)`` pairs, oldest first (sparkline)."""
        with self._lock:
            return list(self._walls.get(tenant, ()))

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._last)

    def rounds_folded(self) -> int:
        with self._lock:
            return self._rounds


_timeline = RoundTimeline()
get_tracer().add_flush_hook(_timeline.on_round)


def get_timeline() -> RoundTimeline:
    """The process-wide timeline every round flush folds into."""
    return _timeline
