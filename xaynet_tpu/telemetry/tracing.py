"""Distributed round tracing: spans across coordinator, ingest, shard
workers, edge tier and SDK (docs/DESIGN.md §16).

PR 1's telemetry is aggregate-only — counters and gauges with no causal
story. This module adds the causal layer: **spans** (name, trace id, span
id, optional parent, monotonic wall) recorded around every stage of a
round, so "where did batch 37 spend its time" is one artifact instead of a
print-debugging session. Stdlib only, same discipline as the registry.

Identity model
--------------

- The **round trace id** is derived deterministically from the round seed
  (``round_trace_id``): the coordinator, every edge, and every SDK
  participant compute the SAME id independently, so one two-tier round
  yields ONE stitched trace without a coordination protocol.
- Cross-process hops (SDK -> REST, edge -> coordinator) additionally carry
  an explicit ``trace_id-span_id`` pair — the ``X-Xaynet-Trace`` header
  and the ``XNEDGE1`` envelope ``trace`` field. The receiver ADOPTS the
  trace id and records the remote span id as a ``link`` attribute (not as
  ``parent``): within one process's export every ``parent`` resolves, so
  the validator can stay strict about orphans.
- Span NAMES are a closed set: every name is registered exactly once via
  :func:`declare_span` (duplicate registration raises), ``Tracer.span``
  refuses undeclared names, and the analysis framework cross-checks the
  declared set against the DESIGN §16 span table (rule ``span``).

Buffers and sampling
--------------------

Spans land in two bounded places:

- the **flight-recorder ring** (``deque(maxlen=ring_size)``) — always on
  while tracing isn't ``off``; this is the "what led up to this" forensic
  buffer the recorder dumps on failure triggers;
- the **per-round buffer** (bounded; overflow counted on
  ``xaynet_trace_spans_dropped_total``) — drained into a Chrome-trace
  (Perfetto-loadable) JSON per round when a ``trace_dir`` is configured.

``XAYNET_TRACE`` picks the mode: ``on`` (default — record + export),
``failure`` (ring only: spans exist for the flight recorder, no per-round
export), ``off`` (spans are no-ops). Failed/degraded rounds are always
covered by the ring regardless of sampling — the ring never samples.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import threading
import time
from collections import deque
from typing import Iterable, Optional

from .registry import get_registry

logger = logging.getLogger("xaynet.telemetry")

TRACE_HEADER = "X-Xaynet-Trace"

_registry = get_registry()
SPANS_TOTAL = _registry.counter(
    "xaynet_trace_spans_total",
    "Spans finished, by subsystem (the prefix before the first dot of the "
    "span name — closed set, see docs/DESIGN.md §16).",
    ("subsystem",),
)
SPANS_DROPPED = _registry.counter(
    "xaynet_trace_spans_dropped_total",
    "Spans dropped because the per-round buffer hit its bound (the "
    "flight-recorder ring still keeps the most recent ones).",
)
TRACE_EXPORTS = _registry.counter(
    "xaynet_trace_exports_total",
    "Per-round Chrome-trace exports, by outcome (written | failed).",
    ("outcome",),
)


class SpanNameError(ValueError):
    """Span name declared twice, or used without a declaration."""


# the process-wide span-name registry: name -> declaring module (for the
# duplicate-declaration diagnostic). The analysis `span` pass mirrors this
# statically and cross-checks it against the DESIGN §16 table.
_SPAN_NAMES: dict[str, str] = {}
_names_lock = threading.Lock()


def declare_span(name: str) -> str:
    """Register one span name exactly once (module import time).

    Returns the name so modules can bind it: ``SPAN_X = declare_span("x.y")``.
    """
    if not name or any(c.isspace() for c in name):
        raise SpanNameError(f"bad span name {name!r}")
    import inspect

    frame = inspect.currentframe()
    module = "?"
    if frame is not None and frame.f_back is not None:
        module = frame.f_back.f_globals.get("__name__", "?")
    with _names_lock:
        owner = _SPAN_NAMES.get(name)
        if owner is not None and owner != module:
            raise SpanNameError(
                f"span name {name!r} already declared by {owner}; "
                "one module owns a span name — import its constant instead"
            )
        _SPAN_NAMES[name] = module
    return name


def declared_span_names() -> dict[str, str]:
    """Snapshot of the declared span names (tests, the analysis pass)."""
    with _names_lock:
        return dict(_SPAN_NAMES)


# the root span every phase span parents to; declared here because the
# tracer itself records it at round end
SPAN_ROUND = declare_span("round")


# span ids are correlation handles, not secrets: a module-level PRNG
# seeded from the OS beats uuid4 by ~25x per id (uuid4 dominated the
# original ~70 us/span cost on the bench box). getrandbits is one C call
# under the GIL, so concurrent recorders never tear it.
_id_rng = random.Random(int.from_bytes(os.urandom(16), "little"))


def new_id() -> str:
    """A fresh 16-hex trace/span id."""
    return f"{_id_rng.getrandbits(64):016x}"


_new_id = new_id


def round_trace_id(round_seed: bytes) -> str:
    """The deterministic per-round trace id every tier derives on its own
    from the public round seed — the stitching key of a distributed round."""
    import hashlib

    return hashlib.sha256(b"xaynet-trace\x00" + round_seed).hexdigest()[:16]


class TraceContext:
    """(trace_id, span_id) — what propagates, ambient or on the wire.

    An empty ``span_id`` pins the TRACE without claiming a parent span
    (e.g. the SDK's round-derived context): children adopt the trace id
    and record no ``parent``, so strict orphan validation holds.
    """

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"TraceContext({self.trace_id}-{self.span_id})"


def format_header(ctx: TraceContext) -> str:
    return f"{ctx.trace_id}-{ctx.span_id}"


def parse_header(value: str | None) -> Optional[TraceContext]:
    """Parse an ``X-Xaynet-Trace`` value; None on anything malformed (an
    attacker-controlled header must never raise out of the REST path)."""
    if not value:
        return None
    trace_id, _, span_id = value.strip().partition("-")
    if not (
        len(trace_id) == 16
        and len(span_id) == 16
        and all(c in "0123456789abcdef" for c in trace_id + span_id)
    ):
        return None
    return TraceContext(trace_id, span_id)


_ctx: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "xaynet_trace_ctx", default=None
)


def current_ctx() -> Optional[TraceContext]:
    """The ambient trace context of this task/thread (None outside spans)."""
    return _ctx.get()


class Span:
    """One finished (or in-flight) span. Walls are monotonic."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "duration",
        "attrs", "error", "thread",
    )

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], start: float, attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start  # time.monotonic()
        self.duration: float = 0.0
        self.attrs = attrs
        self.error: Optional[str] = None
        self.thread = threading.current_thread().name

    @property
    def subsystem(self) -> str:
        return self.name.split(".", 1)[0]

    def to_json(self, anchor: float = 0.0) -> dict:
        out = {
            "name": self.name,
            "trace": self.trace_id,
            "span": self.span_id,
            "ts": round(self.start - anchor, 6),
            "dur": round(self.duration, 6),
            "thread": self.thread,
        }
        if self.parent_id:
            out["parent"] = self.parent_id
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.error:
            out["error"] = self.error
        return out


class _SpanHandle:
    """Context manager for one span: enter/exit is the ONLY way a span
    opens and closes, so every enter has a matching exit on every
    exception path by construction (the analysis ``span`` pass rejects
    non-``with`` uses)."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span
        self._token = None

    @property
    def ctx(self) -> TraceContext:
        return TraceContext(self._span.trace_id, self._span.span_id)

    def set(self, **attrs) -> None:
        """Attach attributes mid-span (e.g. the outcome)."""
        self._span.attrs.update(attrs)

    def __enter__(self) -> "_SpanHandle":
        self._token = _ctx.set(self.ctx)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        _ctx.reset(self._token)
        self._span.duration = time.monotonic() - self._span.start
        if exc is not None:
            self._span.error = f"{type(exc).__name__}: {exc}"
        self._tracer._finish(self._span)


class _NullSpan:
    """The ``off``-mode span: no allocation beyond the singleton, no ctx."""

    __slots__ = ()
    ctx = None

    def set(self, **attrs) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()

_MODES = ("on", "failure", "off")


class Tracer:
    """Process-wide span recorder: bounded ring + per-round export buffer.

    Thread-safe: producers on the event loop, fold workers, and the SDK's
    private loops all record through one lock-guarded append.
    """

    def __init__(
        self,
        mode: str | None = None,
        ring_size: int = 4096,
        round_cap: int = 8192,
        trace_dir: str | None = None,
    ):
        mode = mode or os.environ.get("XAYNET_TRACE", "on")
        if mode not in _MODES:
            logger.warning("unknown XAYNET_TRACE=%r; tracing on", mode)
            mode = "on"
        self.mode = mode
        self.trace_dir = (
            trace_dir if trace_dir is not None else os.environ.get("XAYNET_TRACE_DIR", "")
        )
        self._lock = threading.Lock()
        self._ring: deque[Span] = deque(maxlen=ring_size)  # guarded-by: _lock
        self._round_cap = round_cap
        self._round_spans: list[Span] = []  # guarded-by: _lock
        self._round_id: Optional[int] = None  # guarded-by: _lock
        self._round_trace: Optional[str] = None  # guarded-by: _lock
        self._round_root: Optional[str] = None  # guarded-by: _lock
        self._round_start: float = 0.0  # guarded-by: _lock
        # monotonic anchor for export timestamps (one per process)
        self.anchor = time.monotonic()
        # round-boundary listeners (the flight recorder snapshots registry
        # counters here); fail-soft by contract
        self._round_hooks: list = []
        # round-flush listeners: called with (round_id, spans) when a round
        # window closes (the timeline fold consumes the span buffer here);
        # fail-soft by contract
        self._flush_hooks: list = []

    # -- configuration -----------------------------------------------------

    def configure(self, mode: str | None = None, trace_dir: str | None = None,
                  ring_size: int | None = None) -> None:
        """Runtime (re)configuration — the runner applies settings here."""
        if mode is not None:
            if mode not in _MODES:
                raise ValueError(f"trace mode must be one of {_MODES}, got {mode!r}")
            self.mode = mode
        if trace_dir is not None:
            self.trace_dir = trace_dir
        if ring_size is not None:
            with self._lock:
                self._ring = deque(self._ring, maxlen=ring_size)

    def add_round_hook(self, hook) -> None:
        if hook not in self._round_hooks:
            self._round_hooks.append(hook)

    def add_flush_hook(self, hook) -> None:
        """Register ``hook(round_id, spans)``, called every time a round
        window flushes (``end_round``) with the round's span buffer —
        parents already resolved, ready for in-process analysis."""
        if hook not in self._flush_hooks:
            self._flush_hooks.append(hook)

    # -- recording ---------------------------------------------------------

    def span(self, name: str, ctx: Optional[TraceContext] = None,
             link: Optional[TraceContext] = None, **attrs):
        """Open one span as a context manager.

        Parentage: explicit ``ctx`` wins (worker threads, whose ambient
        context is empty), else the ambient context, else the current
        round's root; a span with no context at all starts a fresh trace.
        ``link`` is a REMOTE context (header/envelope hop): its trace id is
        adopted but the remote span rides in the ``link`` attribute instead
        of ``parent`` — within one process's export every parent resolves.
        """
        if self.mode == "off":
            return _NULL_SPAN
        if name not in _SPAN_NAMES:
            raise SpanNameError(
                f"span name {name!r} was never declared (declare_span)"
            )
        if link is not None:
            attrs["link"] = link.span_id
            span = Span(name, link.trace_id, _new_id(), None, time.monotonic(), attrs)
            return _SpanHandle(self, span)
        parent = ctx if ctx is not None else _ctx.get()
        if parent is None:
            parent = self.round_ctx()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id or None
        else:
            trace_id, parent_id = _new_id(), None
        span = Span(name, trace_id, _new_id(), parent_id, time.monotonic(), attrs)
        return _SpanHandle(self, span)

    def record_span(self, name: str, start: float, duration: float,
                    ctx: Optional[TraceContext] = None, **attrs) -> None:
        """Record a retroactive span (a wait measured across tasks — e.g.
        the intake queue wait — where enter/exit bracketing is impossible).
        ``start`` is a ``time.monotonic()`` reading."""
        if self.mode == "off":
            return
        if name not in _SPAN_NAMES:
            raise SpanNameError(f"span name {name!r} was never declared (declare_span)")
        parent = ctx if ctx is not None else _ctx.get()
        if parent is None:
            parent = self.round_ctx()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id or None
        else:
            trace_id, parent_id = _new_id(), None
        span = Span(name, trace_id, _new_id(), parent_id, start, attrs)
        span.duration = max(0.0, duration)
        self._finish(span)

    def _finish(self, span: Span) -> None:
        SPANS_TOTAL.labels(subsystem=span.subsystem).inc()
        with self._lock:
            self._ring.append(span)
            # the round buffer only accumulates while a round window is
            # open: a process that never calls begin_round (SDK client
            # side) keeps just the bounded ring instead of permanently
            # retaining cap spans and counting phantom drops
            if self._round_id is None:
                return
            if len(self._round_spans) < self._round_cap:
                self._round_spans.append(span)
            else:
                SPANS_DROPPED.inc()

    # -- round windows -----------------------------------------------------

    def begin_round(self, round_id: int, trace_id: str) -> None:
        """Open a round window (flushing the previous round's export) and
        pin the round's trace id + root span. Idempotent for the SAME
        (round, trace): in-process multi-tier tests run the coordinator
        and the edge tier on one tracer, and the edge's round sync must
        not reset the window the coordinator already opened."""
        with self._lock:
            if self._round_id == round_id and self._round_trace == trace_id:
                return
        self.end_round()
        if self.mode == "off":
            return
        with self._lock:
            self._round_id = round_id
            self._round_trace = trace_id
            self._round_root = _new_id()
            self._round_start = time.monotonic()
            self._round_spans = []
        for hook in self._round_hooks:
            try:
                hook(round_id)
            except Exception:  # a telemetry consumer must never fail a round
                logger.exception("trace round hook failed")

    def round_ctx(self) -> Optional[TraceContext]:
        """The current round's root context (worker threads parent here)."""
        with self._lock:
            if self._round_trace is None:
                return None
            return TraceContext(self._round_trace, self._round_root)

    def end_round(self) -> list[Span]:
        """Close the round window: record the root ``round`` span, export
        the Chrome trace when configured, and return the round's spans."""
        with self._lock:
            if self._round_id is None:
                return []
            root = Span(
                SPAN_ROUND,
                self._round_trace,
                self._round_root,
                None,
                self._round_start,
                {"round_id": self._round_id},
            )
            root.duration = time.monotonic() - self._round_start
            self._ring.append(root)
            # the root always lands (it anchors the export), even when the
            # round buffer hit its cap
            self._round_spans.append(root)
            spans, self._round_spans = self._round_spans, []
            round_id = self._round_id
            self._round_id = None
            self._round_trace = None
            self._round_root = None
        SPANS_TOTAL.labels(subsystem=root.subsystem).inc()
        # export contract: every `parent` resolves WITHIN the bundle. A span
        # that started under the previous window (its parent was exported
        # there) demotes the dangling parent to a `link` attribute — same
        # representation as a cross-process hop
        ids = {s.span_id for s in spans}
        for s in spans:
            if s.parent_id and s.parent_id not in ids:
                s.attrs.setdefault("link", s.parent_id)
                s.parent_id = None
        for hook in self._flush_hooks:
            try:
                hook(round_id, spans)
            except Exception:  # a telemetry consumer must never fail a round
                logger.exception("trace flush hook failed")
        if self.trace_dir and self.mode == "on":
            self._export(round_id, spans)
        return spans

    def ring_spans(self) -> list[Span]:
        """Snapshot of the flight-recorder ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def round_spans_snapshot(self) -> tuple[Optional[int], list[Span]]:
        """The open round window's id and a copy of its buffered spans —
        for in-process consumers that need the buffer BEFORE the window
        flushes (the round report's timeline section fires one phase
        earlier than ``end_round``). ``(None, [])`` outside a window."""
        with self._lock:
            if self._round_id is None:
                return None, []
            return self._round_id, list(self._round_spans)

    # -- export ------------------------------------------------------------

    def _export(self, round_id: int, spans: list[Span]) -> None:
        try:
            os.makedirs(self.trace_dir, exist_ok=True)
            # pid discriminator: a coordinator and its edge processes may
            # share one trace_dir (env-inherited in soaks) and both export
            # the SAME round id — without it, last writer wins
            path = os.path.join(
                self.trace_dir, f"round_{round_id}.{os.getpid()}.trace.json"
            )
            with open(path, "w") as f:
                json.dump(to_chrome_trace(spans, anchor=self.anchor), f)
            TRACE_EXPORTS.labels(outcome="written").inc()
            logger.info("[trace] round %d trace written: %s", round_id, path)
        except OSError as err:
            TRACE_EXPORTS.labels(outcome="failed").inc()
            logger.warning("round trace export failed: %s", err)


def to_chrome_trace(spans: Iterable[Span], anchor: float = 0.0) -> dict:
    """Spans -> ``chrome://tracing`` / Perfetto JSON object format.

    One complete (``ph: "X"``) event per span; ``pid`` is the subsystem,
    ``tid`` the recording thread, and the span/trace/parent identities ride
    in ``args`` so the text report and the CI validator can rebuild the
    tree from the export alone.
    """
    from .redact import scrub_attrs

    pids: dict[str, int] = {}
    tids: dict[tuple[int, str], int] = {}
    events: list[dict] = []
    for span in spans:
        pid = pids.setdefault(span.subsystem, len(pids) + 1)
        tid = tids.setdefault((pid, span.thread), len(tids) + 1)
        args = {"trace": span.trace_id, "span": span.span_id}
        if span.parent_id:
            args["parent"] = span.parent_id
        # deny-list scrub before the export hits disk (DESIGN §18): span
        # attrs whose key names secret material leave only a redacted
        # length/digest projection in the Chrome trace
        args.update(scrub_attrs(span.attrs, "trace"))
        if span.error:
            args["error"] = span.error
        events.append(
            {
                "name": span.name,
                "cat": span.subsystem,
                "ph": "X",
                "ts": round((span.start - anchor) * 1e6, 1),
                "dur": round(span.duration * 1e6, 1),
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
    for subsystem, pid in pids.items():
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": subsystem},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


_tracer = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer every subsystem records into by default."""
    return _tracer
