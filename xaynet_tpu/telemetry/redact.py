"""Runtime secret redaction: the telemetry layer's last line of defense.

The static taint pass (``tools/analysis/taint.py``, docs/DESIGN.md §18)
proves at lint time that key material never *flows* into logs, spans,
dumps or reports. This module is the runtime complement for what static
analysis cannot see — values that become secret only dynamically (a seed
fetched off the wire, an attr dict built from parsed input): flight
recorder dumps and Chrome-trace exports pass every attribute through a
deny-list filter before it hits disk, and ``redact()`` is the sanctioned
length/type-only projection for code that must mention a secret at all
(the taint pass treats it as a declassifier).

Every redaction is counted on ``xaynet_redactions_total{site}`` so a
sudden spike — someone started putting secret-keyed values into span
attrs — is an alertable signal, not a silent save.
"""

from __future__ import annotations

import hashlib
import os

from .registry import get_registry

# per-process salt: the digest prefix must correlate two mentions of the
# same secret WITHIN one process's artifacts (that is the forensic need —
# flight dumps and trace exports are per-process) without handing anyone
# holding the artifact an offline dictionary-confirmation oracle for
# low-entropy secrets like a human-chosen edge token
_SALT = os.urandom(16)

REDACTIONS = get_registry().counter(
    "xaynet_redactions_total",
    "Values redacted from telemetry surfaces before leaving the process, "
    "by site (redact = explicit redact() call | flight = flight-recorder "
    "dump filter | trace = Chrome-trace export filter | alerts = SLO "
    "alert-payload filter).",
    ("site",),
)

# attr/field names whose VALUES never leave the process raw. Substring
# match on the lowercased key: 'mask_seed', 'round_seed', 'secret_key',
# 'edge_token', 'keystream_bytes' all hit. 'round_seed' is public by
# protocol but carries zero forensic value in a dump (the derived trace id
# is already there), so the filter stays simple instead of clever.
DENY_SUBSTRINGS = ("seed", "secret", "token", "keystream", "private")
DENY_EXACT = ("sk", "key_bytes")


def _denied(key: str) -> bool:
    low = key.lower()
    return low in DENY_EXACT or any(s in low for s in DENY_SUBSTRINGS)


def redact(value, site: str = "redact") -> str:
    """Length/type-only projection of a secret value.

    Returns ``<redacted TYPE:LEN DIGEST8>`` — the digest prefix is
    sha256 over a per-process random salt plus the value, so it
    correlates two mentions of the same secret within one process's
    artifacts without revealing a byte of it or enabling an offline
    dictionary check. This is the declassifier the taint pass sanctions
    for code that must talk about a secret (error detail, forensic
    attrs).
    """
    if isinstance(value, (bytes, bytearray, memoryview)):
        raw = bytes(value)
    else:
        raw = str(value).encode("utf-8", "replace")
    digest = hashlib.sha256(_SALT + raw).hexdigest()[:8]
    REDACTIONS.labels(site=site).inc()
    return f"<redacted {type(value).__name__}:{len(raw)} {digest}>"


def scrub_attrs(attrs: dict, site: str) -> dict:
    """Deny-list filter for attr dicts headed to disk.

    Recursive over nested dicts (and dicts inside lists/tuples): any entry
    whose key matches the deny list is replaced by its ``redact()``
    projection. Non-denied values pass through untouched — the filter must
    never change the shape consumers (Perfetto, the trace validator,
    soak greps) parse.
    """
    out = {}
    for key, value in attrs.items():
        if _denied(str(key)):
            out[key] = redact(value, site=site)
        elif isinstance(value, dict):
            out[key] = scrub_attrs(value, site)
        elif isinstance(value, (list, tuple)):
            out[key] = [
                scrub_attrs(item, site) if isinstance(item, dict) else item
                for item in value
            ]
        else:
            out[key] = value
    return out
