"""TPU hot-path profiling hooks.

Timing accelerator work honestly means syncing the device: JAX dispatch is
asynchronous, so a wall-clock around the call alone measures dispatch, not
the kernel. ``timed_kernel`` runs an op, blocks until its outputs are ready
(``jax.block_until_ready`` — a no-op for host numpy results) and records
device-synced seconds, element counts and derived elements/sec into the
process registry, plus a per-round accumulator the round report drains.

The sync point serializes dispatch pipelining (e.g. the wire-ingest path
deliberately overlaps the fold with the acceptance-vector fetch), so the
hooks can be disabled wholesale with ``XAYNET_KERNEL_PROFILE=0`` — the ops
then run exactly as before, with zero added synchronization.

Ops recorded by the stack today: ``mask_expand`` (PRNG seed -> mask limbs),
``masked_add`` (the fold), ``wire_unpack``/``wire_ingest`` (device wire
paths), ``unmask`` (modular subtract + decode).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, TypeVar

from .registry import get_registry

T = TypeVar("T")

# sub-millisecond kernels up to minute-scale 25M-element folds
_KERNEL_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_registry = get_registry()
KERNEL_SECONDS = _registry.histogram(
    "xaynet_kernel_seconds",
    "Device-synced wall time of one aggregation kernel invocation.",
    ("op",),
    buckets=_KERNEL_BUCKETS,
)
KERNEL_CALLS = _registry.counter(
    "xaynet_kernel_calls_total", "Aggregation kernel invocations.", ("op",)
)
KERNEL_ELEMENTS = _registry.counter(
    "xaynet_kernel_elements_total", "Group elements processed by kernel.", ("op",)
)
KERNEL_RATE = _registry.gauge(
    "xaynet_kernel_elements_per_second",
    "Throughput of the most recent invocation of each kernel.",
    ("op",),
)
KERNEL_CALIBRATION = _registry.gauge(
    "xaynet_kernel_calibration_seconds",
    "Steady-state fold time per candidate measured by kernel auto-calibration.",
    ("kernel",),
)
KERNEL_FIRST_CALL = _registry.gauge(
    "xaynet_kernel_first_call_seconds",
    "Wall time of each op's first invocation this process — on jit-compiled "
    "device paths this includes XLA/Mosaic compilation, so subtract it from "
    "histogram aggregates for steady-state analysis.",
    ("op",),
)

_round_lock = threading.Lock()
_round_stats: dict[str, dict[str, float]] = {}
_seen_ops: set[str] = set()


def enabled() -> bool:
    """Hot-path sync profiling toggle (``XAYNET_KERNEL_PROFILE=0`` disables)."""
    return os.environ.get("XAYNET_KERNEL_PROFILE", "1") != "0"


def _block(result: T) -> T:
    """Wait for device work backing ``result`` (pytree-safe, numpy-safe).

    Only the jax import is guarded: a device error surfacing at the sync
    point must PROPAGATE — callers like kernel auto-calibration rely on it
    (a Pallas candidate that fails on invocation falls back to XLA only if
    the failure is visible here)."""
    try:
        import jax
    except ImportError:  # telemetry stays usable in jax-less tooling
        return result
    return jax.block_until_ready(result)


def record(op: str, seconds: float, elements: int) -> None:
    """Record one kernel invocation into the registry and the round window."""
    KERNEL_SECONDS.labels(op=op).observe(seconds)
    KERNEL_CALLS.labels(op=op).inc()
    KERNEL_ELEMENTS.labels(op=op).inc(elements)
    if seconds > 0:
        KERNEL_RATE.labels(op=op).set(elements / seconds)
    with _round_lock:
        if op not in _seen_ops:
            _seen_ops.add(op)
            KERNEL_FIRST_CALL.labels(op=op).set(seconds)
        stats = _round_stats.setdefault(
            op, {"calls": 0, "seconds": 0.0, "elements": 0}
        )
        stats["calls"] += 1
        stats["seconds"] += seconds
        stats["elements"] += elements


def timed_kernel(op: str, elements: int, fn: Callable[[], T]) -> T:
    """Run ``fn``, sync its outputs, record the timing; pass-through (no
    sync, no record) when profiling is disabled."""
    if not enabled():
        return fn()
    t0 = time.perf_counter()
    result = _block(fn())
    record(op, time.perf_counter() - t0, elements)
    return result


def measure(fn: Callable[[], T]) -> tuple[T, float]:
    """(result, device-synced seconds) — the primitive for calibration code
    that needs the number itself rather than a registry record."""
    t0 = time.perf_counter()
    result = _block(fn())
    return result, time.perf_counter() - t0


def record_calibration(kernel: str, seconds: float) -> None:
    KERNEL_CALIBRATION.labels(kernel=kernel).set(seconds)


def drain_round_stats() -> dict[str, dict[str, float]]:
    """Per-op stats accumulated since the last drain (with derived
    elements/sec); resets the window. Consumed by the round report."""
    with _round_lock:
        stats = dict(_round_stats)
        _round_stats.clear()
    out = {}
    for op, s in stats.items():
        out[op] = dict(s)
        out[op]["elements_per_sec"] = (
            round(s["elements"] / s["seconds"], 3) if s["seconds"] > 0 else 0.0
        )
    return out
