"""Unified telemetry layer: registry, kernel profiling, round reports.

- ``registry``  — Prometheus-style in-process metrics (counters, gauges,
  histograms, labels, text exposition) behind ``GET /metrics``;
- ``profiling`` — device-synced kernel timing hooks for the aggregation
  hot path (``XAYNET_KERNEL_PROFILE=0`` disables the sync points);
- ``report``    — per-round JSON report emitter (JSONL artifact);
- ``bridge``    — the reference eight-measurement recorder surface on top
  of the registry, forwarding to the legacy Jsonl/Influx sinks.
"""

from .bridge import BridgedMetrics as BridgedMetrics
from .registry import (
    DEFAULT_BUCKETS as DEFAULT_BUCKETS,
    MetricError as MetricError,
    MetricsRegistry as MetricsRegistry,
    get_registry as get_registry,
)
from .report import RoundReporter as RoundReporter
