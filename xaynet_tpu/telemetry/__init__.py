"""Unified telemetry layer: registry, kernel profiling, round reports.

- ``registry``  — Prometheus-style in-process metrics (counters, gauges,
  histograms, labels, text exposition) behind ``GET /metrics``;
- ``profiling`` — device-synced kernel timing hooks for the aggregation
  hot path (``XAYNET_KERNEL_PROFILE=0`` disables the sync points);
- ``report``    — per-round JSON report emitter (JSONL artifact);
- ``bridge``    — the reference eight-measurement recorder surface on top
  of the registry, forwarding to the legacy Jsonl/Influx sinks;
- ``tracing``   — the distributed round-tracing span layer (trace ids,
  bounded buffers, Chrome-trace export — docs/DESIGN.md §16);
- ``timeline``  — the always-on round-wall profiler: a streaming fold
  over each flushed round's span buffer into the
  ``xaynet_round_wall_seconds{tenant}`` histogram and a per-phase
  self-time/overlap decomposition (docs/DESIGN.md §20);
- ``slo``       — per-tenant SLO engine: multi-window burn-rate alerts
  over registry deltas, ``GET /alerts`` payloads, flight-recorder pages
  (docs/DESIGN.md §20);
- ``recorder``  — the flight recorder dumping span ring + registry deltas
  on failure triggers;
- ``redact``    — runtime secret redaction: ``redact()`` (the sanctioned
  length/type-only projection the taint pass treats as a declassifier)
  and the deny-list ``scrub_attrs`` filter applied to flight dumps and
  Chrome-trace exports before they hit disk (docs/DESIGN.md §18).
"""

from .bridge import BridgedMetrics as BridgedMetrics
from .recorder import FlightRecorder as FlightRecorder, flight_dump as flight_dump
from .redact import redact as redact, scrub_attrs as scrub_attrs
from .registry import (
    DEFAULT_BUCKETS as DEFAULT_BUCKETS,
    MetricError as MetricError,
    MetricsRegistry as MetricsRegistry,
    get_registry as get_registry,
)
from .report import RoundReporter as RoundReporter
from .slo import SloEngine as SloEngine, get_engine as get_slo_engine
from .timeline import RoundTimeline as RoundTimeline, get_timeline as get_timeline
from .tracing import (
    TraceContext as TraceContext,
    Tracer as Tracer,
    declare_span as declare_span,
    get_tracer as get_tracer,
    round_trace_id as round_trace_id,
    to_chrome_trace as to_chrome_trace,
)
