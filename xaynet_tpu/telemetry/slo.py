"""Per-tenant SLO engine: multi-window burn-rate alerts over registry
deltas (docs/DESIGN.md §20).

Three SLOs per tenant, all fed by signals the process already records:

- ``round_wall``  — fraction of rounds whose end-to-end wall
  (``telemetry.timeline``) stays under the tenant's ``round_wall_s``
  target; the error budget is ``round_wall_budget`` (allowed fraction of
  slow rounds);
- ``degraded``    — fraction of rounds that closed a request window
  degraded/timeout (PR 7's liveness machinery); budget
  ``degraded_budget``;
- ``shed``        — ingress sheds (HTTP 429) as a fraction of admission
  decisions, read as deltas of the admission counters
  (``xaynet_tenant_ingest_shed_total{tenant}`` per tenant, the global
  ``xaynet_ingest_{admitted,shed}_total`` as the traffic denominator);
  budget ``shed_budget``.

Evaluation is the standard multi-window burn-rate scheme: at every round
boundary the engine appends one timestamped sample of the cumulative
(good, bad) event counts per SLO and computes the burn rate — (bad
fraction over the window) / budget — over a FAST and a SLOW window. An
alert fires only when BOTH windows burn (the fast window makes the alert
prompt, the slow window keeps a single spike from paging):
``page`` at ``page_burn``, ``warn`` at ``warn_burn``. Transitions land on
``xaynet_slo_alerts_total{slo,severity}`` and in a bounded recent-alert
ring (``GET /alerts``, the ``/statusz`` console), and a page-severity
transition routes through the flight recorder (``slo-page`` trigger) so
the forensic bundle of the burn is written the moment it starts, not when
an operator gets around to it. ``xaynet_slo_budget_remaining{tenant,slo}``
and ``xaynet_slo_burn_rate{tenant,slo}`` expose the live state.

Like every telemetry consumer the engine is fail-soft and stdlib-only;
with no ``[slo]`` section configured it runs with generous defaults (the
timeline signal stays always-on, alerts effectively never fire).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from .redact import scrub_attrs
from .registry import get_registry

_registry = get_registry()
SLO_BUDGET = _registry.gauge(
    "xaynet_slo_budget_remaining",
    "Fraction of the slow-window error budget left, by tenant and SLO "
    "(1 = untouched, 0 = exhausted, negative = overspent; §20).",
    ("tenant", "slo"),
)
SLO_BURN = _registry.gauge(
    "xaynet_slo_burn_rate",
    "Fast-window burn rate, by tenant and SLO (1.0 = spending exactly "
    "the error budget; §20).",
    ("tenant", "slo"),
)
SLO_ALERTS = _registry.counter(
    "xaynet_slo_alerts_total",
    "Burn-rate alert transitions, by SLO and severity (warn | page; §20).",
    ("slo", "severity"),
)

SLOS = ("round_wall", "degraded", "shed")
_SEVERITY_RANK = {"": 0, "warn": 1, "page": 2}
_RING_SIZE = 64
# sample retention: enough history for the slow window plus one sample
# before it (delta anchoring), bounded so a fast round cadence cannot
# grow the deque without limit
_MAX_SAMPLES = 4096


class SloConfig:
    """Resolved engine configuration (defaults when no [slo] section)."""

    def __init__(
        self,
        enabled: bool = True,
        round_wall_s: float = 600.0,
        tenant_round_wall_s: Optional[dict[str, float]] = None,
        round_wall_budget: float = 0.05,
        degraded_budget: float = 0.1,
        shed_budget: float = 0.05,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        warn_burn: float = 6.0,
        page_burn: float = 14.4,
    ):
        self.enabled = enabled
        self.round_wall_s = round_wall_s
        self.tenant_round_wall_s = dict(tenant_round_wall_s or {})
        self.round_wall_budget = round_wall_budget
        self.degraded_budget = degraded_budget
        self.shed_budget = shed_budget
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.warn_burn = warn_burn
        self.page_burn = page_burn

    def target_for(self, tenant: str) -> float:
        return self.tenant_round_wall_s.get(tenant, self.round_wall_s)

    def budget_for(self, slo: str) -> float:
        return {
            "round_wall": self.round_wall_budget,
            "degraded": self.degraded_budget,
            "shed": self.shed_budget,
        }[slo]


def _burn(samples, now: float, window: float, slo: str, budget: float) -> float:
    """Burn rate over ``[now - window, now]`` from cumulative samples:
    (bad delta / total delta) / budget; 0.0 with no traffic."""
    if not samples:
        return 0.0
    # anchor = the state AT window start: the last sample before the
    # window, or the zero state when the whole history is inside it (a
    # samples[0] anchor would silently drop the first round's events
    # until enough history ages out of the window)
    anchor = None
    for s in samples:
        if s["ts"] >= now - window:
            break
        anchor = s
    anchor_bad, anchor_total = anchor[slo] if anchor is not None else (0.0, 0.0)
    latest = samples[-1]
    total = latest[slo][1] - anchor_total
    bad = latest[slo][0] - anchor_bad
    if total <= 0 or budget <= 0:
        return 0.0
    return (bad / total) / budget


class SloEngine:
    """Round-driven burn-rate evaluator; one per process (``get_engine``)."""

    def __init__(self, config: Optional[SloConfig] = None):
        self.config = config or SloConfig()
        self._lock = threading.Lock()
        # per-tenant cumulative event counts and timestamped samples
        self._counts: dict[str, dict[str, list[float]]] = {}  # guarded-by: _lock
        self._samples: dict[str, deque] = {}  # guarded-by: _lock
        self._active: dict[tuple[str, str], str] = {}  # guarded-by: _lock
        self._ring: deque = deque(maxlen=_RING_SIZE)  # guarded-by: _lock
        self._transition_hook = None  # set via set_transition_hook

    def configure(self, config: SloConfig) -> None:
        self.config = config

    def set_transition_hook(self, hook) -> None:
        """Install a callback fired on EVERY severity transition —
        escalations AND de-escalations back to ok — as ``hook(tenant, slo,
        severity)`` with severity one of ``"" | "warn" | "page"``. The
        tenancy lifecycle uses this to demote a burn-paging tenant's
        scheduler priority and restore it when the burn recovers. Called
        outside the engine lock; must be fail-soft and non-blocking."""
        self._transition_hook = hook

    # -- shed signal: registry deltas ---------------------------------------

    @staticmethod
    def _shed_totals(tenant: str) -> tuple[float, float]:
        """Cumulative (sheds, admission decisions) for ``tenant`` from the
        live registry: the per-tenant shed counter when the tenancy layer
        runs, the global admission counters as the traffic denominator
        (single-tenant deployments shed on the global counter only)."""
        reg = get_registry()
        shed = reg.sample_value("xaynet_tenant_ingest_shed_total", {"tenant": tenant})
        global_shed = reg.sample_value("xaynet_ingest_shed_total") or 0.0
        if shed is None:
            # no per-tenant series: the bare-route tenant owns the global
            shed = global_shed if tenant == "default" else 0.0
        admitted = reg.sample_value("xaynet_ingest_admitted_total") or 0.0
        return float(shed), float(admitted + global_shed)

    # -- round boundary (called by the timeline fold) ------------------------

    def on_round(
        self, tenant: str, round_id: int, wall_s: float, degraded: bool
    ) -> None:
        if not self.config.enabled:
            return
        now = time.monotonic()
        target = self.config.target_for(tenant)
        sheds, decisions = self._shed_totals(tenant)
        with self._lock:
            counts = self._counts.setdefault(
                tenant, {"rounds": [0.0, 0.0], "degraded_rounds": [0.0, 0.0]}
            )
            counts["rounds"][1] += 1
            if wall_s > target:
                counts["rounds"][0] += 1
            counts["degraded_rounds"][1] += 1
            if degraded:
                counts["degraded_rounds"][0] += 1
            sample = {
                "ts": now,
                # (bad, total) cumulative pairs per SLO
                "round_wall": tuple(counts["rounds"]),
                "degraded": tuple(counts["degraded_rounds"]),
                "shed": (sheds, decisions),
            }
            samples = self._samples.setdefault(tenant, deque(maxlen=_MAX_SAMPLES))
            samples.append(sample)
            horizon = now - 2 * self.config.slow_window_s
            while len(samples) > 1 and samples[0]["ts"] < horizon:
                samples.popleft()
        self._evaluate(tenant, round_id, now)

    # -- evaluation ----------------------------------------------------------

    def _evaluate(self, tenant: str, round_id: int, now: float) -> None:
        cfg = self.config
        with self._lock:
            samples = list(self._samples.get(tenant, ()))
        transitions: list[dict] = []
        changed: list[tuple[str, str]] = []  # (slo, severity), any direction
        for slo in SLOS:
            budget = cfg.budget_for(slo)
            fast = _burn(samples, now, cfg.fast_window_s, slo, budget)
            slow = _burn(samples, now, cfg.slow_window_s, slo, budget)
            SLO_BURN.labels(tenant=tenant, slo=slo).set(round(fast, 4))
            # budget remaining over the slow window: 1 - (bad / (total *
            # budget)); burn_slow IS that consumed fraction scaled by the
            # window, so remaining falls out directly
            SLO_BUDGET.labels(tenant=tenant, slo=slo).set(round(1.0 - slow, 4))
            effective = min(fast, slow)  # both windows must burn
            if effective >= cfg.page_burn:
                severity = "page"
            elif effective >= cfg.warn_burn:
                severity = "warn"
            else:
                severity = ""
            with self._lock:
                previous = self._active.get((tenant, slo), "")
                if severity == previous:
                    continue
                self._active[(tenant, slo)] = severity
                entry = {
                    "ts": round(time.time(), 3),
                    "tenant": tenant,
                    "slo": slo,
                    "severity": severity or "ok",
                    "previous": previous or "ok",
                    "round_id": round_id,
                    "burn_fast": round(fast, 3),
                    "burn_slow": round(slow, 3),
                }
                # defense-in-depth (DESIGN §18): alert payloads leave the
                # process via /alerts and /statusz — scrub before they are
                # ever stored, not at render time
                self._ring.append(scrub_attrs(entry, "alerts"))
            changed.append((slo, severity))
            if _SEVERITY_RANK[severity] > _SEVERITY_RANK[previous]:
                transitions.append(entry)
        hook = self._transition_hook
        if hook is not None:
            for slo, severity in changed:
                try:
                    hook(tenant, slo, severity)
                except Exception:  # fail-soft: feedback must not sink a round
                    import logging

                    logging.getLogger("xaynet.telemetry").exception(
                        "slo transition hook failed"
                    )
        for entry in transitions:
            SLO_ALERTS.labels(slo=entry["slo"], severity=entry["severity"]).inc()
            if entry["severity"] == "page":
                # forensic bundle at burn start: the span ring + counter
                # deltas of the rounds that spent the budget
                from .recorder import flight_dump

                flight_dump(
                    "slo-page",
                    f"tenant {entry['tenant']} {entry['slo']} burn "
                    f"{entry['burn_fast']}x (slow {entry['burn_slow']}x)",
                    tenant=entry["tenant"],
                    slo=entry["slo"],
                    round_id=entry["round_id"],
                    burn_fast=entry["burn_fast"],
                    burn_slow=entry["burn_slow"],
                )

    # -- readers (REST endpoints, console, tests) ----------------------------

    def active_alerts(self) -> list[dict]:
        """Currently-firing alerts (severity warn/page), sorted."""
        with self._lock:
            return [
                {"tenant": tenant, "slo": slo, "severity": severity}
                for (tenant, slo), severity in sorted(self._active.items())
                if severity
            ]

    def recent_alerts(self) -> list[dict]:
        """The bounded transition ring, oldest first (already scrubbed)."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def burn_snapshot(self, tenant: str) -> dict[str, dict[str, float]]:
        """Live burn/budget gauges for one tenant (console section)."""
        out: dict[str, dict[str, float]] = {}
        reg = get_registry()
        for slo in SLOS:
            labels = {"tenant": tenant, "slo": slo}
            burn = reg.sample_value("xaynet_slo_burn_rate", labels)
            budget = reg.sample_value("xaynet_slo_budget_remaining", labels)
            if burn is None and budget is None:
                continue
            out[slo] = {
                "burn_rate": burn or 0.0,
                "budget_remaining": 1.0 if budget is None else budget,
            }
        return out

    def alerts_payload(self) -> dict:
        """The ``GET /alerts`` JSON body: active alerts + recent-transition
        ring + the engine's targets, scrubbed (§18) before export."""
        cfg = self.config
        payload = {
            "enabled": cfg.enabled,
            "targets": {
                "round_wall_s": cfg.round_wall_s,
                "tenants": dict(cfg.tenant_round_wall_s),
                "round_wall_budget": cfg.round_wall_budget,
                "degraded_budget": cfg.degraded_budget,
                "shed_budget": cfg.shed_budget,
                "fast_window_s": cfg.fast_window_s,
                "slow_window_s": cfg.slow_window_s,
                "warn_burn": cfg.warn_burn,
                "page_burn": cfg.page_burn,
            },
            "active": self.active_alerts(),
            "recent": self.recent_alerts(),
        }
        return scrub_attrs(payload, "alerts")


_engine = SloEngine()


def get_engine() -> SloEngine:
    """The process-wide SLO engine (configured by the runner)."""
    return _engine


def configure(settings) -> None:
    """Apply a ``SloSettings`` section (``server.settings``) to the engine.

    Accepts any object with the section's attributes so telemetry stays
    import-independent from the server package.
    """
    _engine.configure(
        SloConfig(
            enabled=settings.enabled,
            round_wall_s=settings.round_wall_s,
            tenant_round_wall_s=settings.tenant_targets(),
            round_wall_budget=settings.round_wall_budget,
            degraded_budget=settings.degraded_budget,
            shed_budget=settings.shed_budget,
            fast_window_s=settings.fast_window_s,
            slow_window_s=settings.slow_window_s,
            warn_burn=settings.warn_burn,
            page_burn=settings.page_burn,
        )
    )
