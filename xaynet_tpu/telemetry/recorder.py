"""Flight recorder: a forensic "what led up to this" artifact on failure.

The tracer's bounded ring always holds the most recent spans (streaming
batch/shard spans included); this module pairs it with a per-round snapshot
of the registry's counters and dumps both as ONE JSON bundle when a failure
trigger fires:

- ``pipeline-poison``   — the streaming fold pipeline poisoned permanently;
- ``degraded-close``    — a phase window closed in degraded mode;
- ``phase-timeout``     — a window closed below quorum (PhaseTimeout);
- ``breaker-open``      — a resilience circuit breaker opened;
- ``edge-ship-drop``    — an edge dropped a sealed envelope (retries
  exhausted / upstream unreachable);
- ``slo-page``          — a page-severity SLO burn-rate alert fired
  (``telemetry.slo``): the bundle is the forensics of the rounds that
  spent the error budget.

Dumps are rate-limited (at most one per trigger per
``_MIN_INTERVAL_S``, ``_MAX_DUMPS`` per process) so a crash-looping
component cannot fill a disk, and every dump path is logged at WARNING —
chaos soaks grep for it. The dump directory comes from
``XAYNET_FLIGHT_DIR`` (the runner overrides it from ``[metrics]
flight_dir``); the default lands under the system temp dir so the recorder
works in any process (edge, bench, tests) without configuration.

Everything here is fail-soft by contract: a broken disk must never turn a
degraded close into a crashed coordinator.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Optional

from .redact import scrub_attrs
from .registry import get_registry
from .tracing import get_tracer

logger = logging.getLogger("xaynet.telemetry")

FLIGHT_DUMPS = get_registry().counter(
    "xaynet_flight_dumps_total",
    "Flight-recorder dumps written, by trigger (pipeline-poison | "
    "degraded-close | phase-timeout | breaker-open | edge-ship-drop | "
    "slo-page).",
    ("trigger",),
)

_MIN_INTERVAL_S = 5.0  # per-trigger floor between dumps
_MAX_DUMPS = 64  # per-process ceiling (a crash loop stops writing, not failing)


def default_dir() -> str:
    return os.environ.get("XAYNET_FLIGHT_DIR", "") or os.path.join(
        tempfile.gettempdir(), "xaynet_flight"
    )


class FlightRecorder:
    """Ring + registry-delta dumper; one per process (``get_recorder``)."""

    def __init__(self, directory: str | None = None):
        self._dir = directory
        self._lock = threading.Lock()
        self._last_dump: dict[str, float] = {}  # guarded-by: _lock
        self._dumps = 0  # guarded-by: _lock
        self._round_id: Optional[int] = None  # guarded-by: _lock
        self._baseline: dict[str, float] = {}  # guarded-by: _lock
        self.last_path: Optional[str] = None  # test/soak observability
        get_tracer().add_round_hook(self.on_round)

    @property
    def directory(self) -> str:
        return self._dir or default_dir()

    def configure(self, directory: str | None) -> None:
        self._dir = directory or None

    # -- round boundary ----------------------------------------------------

    def on_round(self, round_id: int) -> None:
        """Round-begin hook (registered on the tracer): snapshot counters so
        a dump can show WHAT MOVED this round, not absolute totals."""
        with self._lock:
            self._round_id = round_id
            self._baseline = self._counter_snapshot()

    @staticmethod
    def _counter_snapshot() -> dict[str, float]:
        snap: dict[str, float] = {}
        reg = get_registry()
        # private-ish iteration kept inside telemetry (this module and the
        # registry are one subsystem). Histograms contribute their _sum and
        # _count series (latency evidence — "update handling took 40s this
        # round" is exactly what a forensic bundle is for); the per-bucket
        # vectors stay out, they would bloat the bundle without adding a
        # story the sum/count pair doesn't tell
        with reg._lock:
            families = list(reg._families.values())
        for family in families:
            for labelvalues, child in family.children():
                label = ",".join(labelvalues)
                if family.kind == "histogram":
                    suffix = f"{{{label}}}" if label else ""
                    snap[f"{family.name}_sum{suffix}"] = child.sum
                    snap[f"{family.name}_count{suffix}"] = float(child.count)
                    continue
                key = f"{family.name}{{{label}}}" if label else family.name
                snap[key] = child.value
        return snap

    def _deltas(self) -> dict[str, dict[str, float]]:
        now = self._counter_snapshot()
        with self._lock:
            base = dict(self._baseline)
        out: dict[str, dict[str, float]] = {}
        for key, value in now.items():
            before = base.get(key, 0.0)
            if value != before:
                out[key] = {"before": before, "now": value}
        return out

    # -- dumping -----------------------------------------------------------

    def dump(self, trigger: str, detail: str = "", **attrs) -> Optional[str]:
        """Write one forensic bundle; returns its path (None if suppressed
        by rate limiting or on any write failure)."""
        now = time.monotonic()
        with self._lock:
            if self._dumps >= _MAX_DUMPS:
                return None
            last = self._last_dump.get(trigger, -1e9)
            if now - last < _MIN_INTERVAL_S:
                return None
            self._last_dump[trigger] = now
            self._dumps += 1
            round_id = self._round_id
        tracer = get_tracer()
        # defense-in-depth (DESIGN §18): the static taint pass proves no
        # key material flows here at lint time; the deny-list scrub covers
        # what static analysis cannot see (values that became secret
        # dynamically) before the bundle hits disk
        bundle = {
            "trigger": trigger,
            "detail": detail,
            "attrs": scrub_attrs(attrs, "flight"),
            "ts": round(time.time(), 3),
            "round_id": round_id,
            "trace_id": (tracer.round_ctx().trace_id if tracer.round_ctx() else None),
            "ring": [
                scrub_attrs(s.to_json(anchor=tracer.anchor), "flight")
                for s in tracer.ring_spans()
            ],
            "metrics_delta": self._deltas(),
        }
        try:
            directory = self.directory
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(
                directory, f"flight_{int(time.time() * 1000)}_{trigger}.json"
            )
            with open(path, "w") as f:
                json.dump(bundle, f)
        except OSError as err:
            logger.warning("flight-recorder dump failed (%s): %s", trigger, err)
            return None
        FLIGHT_DUMPS.labels(trigger=trigger).inc()
        self.last_path = path
        logger.warning("[flight] %s: dump written to %s (%s)", trigger, path, detail)
        return path


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def flight_dump(trigger: str, detail: str = "", **attrs) -> Optional[str]:
    """Module-level trigger entry point; NEVER raises (failure paths call
    this while already handling an error — a recorder bug must not mask
    the original failure)."""
    try:
        return get_recorder().dump(trigger, detail, **attrs)
    except Exception:
        logger.exception("flight recorder failed")
        return None
