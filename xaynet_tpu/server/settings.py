"""Coordinator configuration: TOML file + environment overrides.

Functional port of the reference's layered settings (reference:
rust/xaynet-server/src/settings/mod.rs): sections [log], [api], [pet],
[mask], [model], [metrics], [redis]/[storage], [restore]; env overrides use
``XAYNET__SECTION__KEY``; cross-field invariants are validated on load
(count min<=max with protocol floors, time min<=max, probability ranges —
settings/mod.rs:307-376).
"""

from __future__ import annotations

import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: env/default settings still work
    tomllib = None
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core.mask.config import BoundType, DataType, GroupType, MaskConfig, ModelType
from ..core.message import SUM_COUNT_MIN, UPDATE_COUNT_MIN
from ..utils.kernels import FOLD_KERNELS


class SettingsError(ValueError):
    """Invalid or inconsistent configuration."""


@dataclass
class CountSettings:
    min: int
    max: int
    # liveness quorum (quorum <= min <= max): once time.min has elapsed and
    # arrivals stall, a phase with accepted >= quorum closes successfully in
    # DEGRADED mode instead of waiting for count.min and timing out. None
    # means quorum == min: no degraded completion for this phase.
    quorum: Optional[int] = None

    @property
    def effective_quorum(self) -> int:
        """The quorum actually enforced (clamped so quorum <= min always
        holds even after an adaptive controller shrank ``min``)."""
        return self.min if self.quorum is None else min(self.quorum, self.min)


@dataclass
class TimeSettings:
    min: float
    max: float


@dataclass
class PhaseSettings:
    prob: float
    count: CountSettings
    time: TimeSettings


@dataclass
class Sum2Settings:
    count: CountSettings
    time: TimeSettings


@dataclass
class PetSettings:
    sum: PhaseSettings
    update: PhaseSettings
    sum2: Sum2Settings

    def validate(self) -> None:
        for name, phase, floor in (
            ("sum", self.sum, SUM_COUNT_MIN),
            ("update", self.update, UPDATE_COUNT_MIN),
        ):
            if not (0.0 < phase.prob <= 1.0) if name == "sum" else not (0.0 <= phase.prob < 1.0):
                raise SettingsError(f"pet.{name}.prob out of range")
            if phase.count.min < floor:
                raise SettingsError(f"pet.{name}.count.min must be >= {floor}")
            if phase.count.max < phase.count.min:
                raise SettingsError(f"pet.{name}.count.max must be >= count.min")
            if phase.time.max < phase.time.min:
                raise SettingsError(f"pet.{name}.time.max must be >= time.min")
            self._validate_quorum(name, phase.count, floor)
        if self.sum2.count.min < SUM_COUNT_MIN:
            raise SettingsError("pet.sum2.count.min must be >= 1")
        if self.sum2.count.max < self.sum2.count.min:
            raise SettingsError("pet.sum2.count.max must be >= count.min")
        if self.sum2.time.max < self.sum2.time.min:
            raise SettingsError("pet.sum2.time.max must be >= time.min")
        self._validate_quorum("sum2", self.sum2.count, SUM_COUNT_MIN)

    @staticmethod
    def _validate_quorum(name: str, count: CountSettings, floor: int) -> None:
        if count.quorum is None:
            return
        if count.quorum < floor:
            raise SettingsError(f"pet.{name}.count.quorum must be >= {floor}")
        if count.quorum > count.min:
            raise SettingsError(f"pet.{name}.count.quorum must be <= count.min")


@dataclass
class MaskSettings:
    group_type: GroupType = GroupType.PRIME
    data_type: DataType = DataType.F32
    bound_type: BoundType = BoundType.B0
    model_type: ModelType = ModelType.M3
    # pre-mask quantization level (docs/DESIGN.md §17): level q divides the
    # fixed-point scale by 10^q, shrinking the group order — and with it
    # limb count, wire width, and every mask/fold/transfer byte — at the
    # price of 10^q coarser weights. 0 = the exact catalogue config. The
    # level rides in the round params' mask-config bytes, so participants
    # follow automatically; gate accuracy per workload (the cifar_lenet
    # example carries the reference gate).
    quant: int = 0

    def to_config(self) -> MaskConfig:
        return MaskConfig(
            self.group_type, self.data_type, self.bound_type, self.model_type, self.quant
        )


@dataclass
class ModelSettings:
    length: int = 4


@dataclass
class ApiSettings:
    bind_address: str = "127.0.0.1:8081"
    tls_certificate: Optional[str] = None
    tls_key: Optional[str] = None
    tls_client_auth: Optional[str] = None

    def validate(self) -> None:
        if (self.tls_certificate is None) != (self.tls_key is None):
            raise SettingsError("api TLS requires both certificate and key")


@dataclass
class StorageSettings:
    backend: str = "memory"  # memory | filesystem | s3 (models)
    model_dir: str = "./global_models"
    # coordinator dictionary backend: memory | file | redis
    coordinator: str = "memory"
    redis_host: str = "127.0.0.1"
    redis_port: int = 6379
    redis_db: int = 0
    # s3 backend (Minio/GCS-interop/AWS; reference settings/s3.rs)
    s3_endpoint: str = "http://127.0.0.1:9000"
    s3_bucket: str = "global-models"
    s3_access_key: str = ""
    s3_secret_key: str = ""
    s3_region: str = "us-east-1"


@dataclass
class RestoreSettings:
    enable: bool = False


@dataclass
class MetricsSettings:
    enable: bool = False
    sink: str = "log"  # log | jsonl | influx (file) | influx-http (network)
    path: str = "./metrics.jsonl"
    url: str = "http://127.0.0.1:8086"  # influx-http write endpoint
    database: str = "metrics"
    # per-round JSON report artifact (JSONL; empty disables). Independent of
    # `enable`: the in-process telemetry registry is always on — enable/sink
    # only control the external line-protocol export.
    round_report_path: str = ""
    # distributed round tracing (docs/DESIGN.md §16): "on" records spans
    # and exports one Chrome-trace JSON per round (when trace_dir is set);
    # "failure" keeps only the bounded flight-recorder ring (spans exist
    # for failure forensics, no per-round export); "off" makes spans no-ops.
    # "" (the default) defers to XAYNET_TRACE (default on) — an explicit
    # config value overrides the env
    trace: str = ""
    # per-round Chrome-trace export directory (empty disables the export;
    # the ring/flight recorder is unaffected)
    trace_dir: str = ""
    # flight-recorder dump directory ("" = XAYNET_FLIGHT_DIR, else the
    # system temp dir)
    flight_dir: str = ""


@dataclass
class LoggingSettings:
    filter: str = "info"


@dataclass
class AggregationSettings:
    device: bool = False  # fold updates on the TPU mesh instead of host numpy
    batch_size: int = 64  # staged updates per device fold
    # fold kernel when device=True: auto (calibrate on the first flush —
    # XLA vs Pallas on accelerators, XLA vs the native host u64 fold on
    # CPU), xla, pallas, pallas-interpret (CI oracle path), or native-u64
    # (host C++ single-pass fold; falls back to xla when unavailable)
    kernel: str = "auto"
    # streaming pipeline (device=True): how many submitted fold batches may
    # be in flight behind the fold worker before flush() backpressures
    dispatch_ahead: int = 2
    # pre-allocated host staging buffers (each batch_size x model-sized);
    # batch N+1 stages into one while batch N folds — >= dispatch_ahead + 1
    # for full overlap, minimum 2
    staging_buffers: int = 3
    # shard-parallel streaming fold (device=True on a multi-device mesh):
    # one fold worker per mesh device with per-shard staging rings and
    # donated per-shard accumulators; drain() is the cross-shard barrier.
    # false forces the legacy single FIFO fold worker (the mesh-sharded
    # single-program fold); single-device meshes ignore the flag
    shard_parallel: bool = True
    # per-shard native fold thread budget (native-u64 kernel only): 0
    # splits the process-wide budget (XAYNET_NATIVE_THREADS / 2x cores)
    # across the shards; > 0 pins threads per shard
    shard_threads: int = 0
    # packed byte-planar staging (docs/DESIGN.md §17): planar update
    # batches stage as ceil(log2(order)/8)-byte planes instead of full
    # uint32 limb planes — bpn/(4L) of the ring memory and host->device
    # bytes (75% for the standard 2-limb f32 configs), byte-identical
    # aggregate. Auto-skipped when the order fills its limbs exactly
    packed_staging: bool = True
    # device wire ingest (requires device=true): Update masked models are
    # parsed LAZILY (raw element block kept), and unpack + per-update
    # element validity + fold all run on the accelerator — the coordinator
    # never executes the host element parse. Rejection semantics: an
    # invalid element fails validate_aggregation (message rejected before
    # its seed-dict insert) instead of the eager parse's DecodeError — the
    # same update rejected, one pipeline stage later.
    wire_ingest: bool = False


@dataclass
class IngestSettings:
    """Admission-controlled batched ingest (``xaynet_tpu.ingest``).

    Defaults keep single-node behavior identical to the direct path: the
    pipeline is off unless enabled, and when enabled the bounds are generous
    enough that an un-saturated coordinator never sheds.
    """

    enabled: bool = False
    # bounded intake topology: total capacity = shards * queue_bound
    shards: int = 2
    queue_bound: int = 1024  # per-shard ceiling (hard bound, never exceeded)
    # admission hysteresis as fractions of total capacity: shed at/above
    # high, resume below low (low <= high)
    high_watermark: float = 0.8
    low_watermark: float = 0.5
    # decrypt worker pool: drain up to max_batch messages per thread-pool
    # hop, waiting at most linger_ms for the batch to fill
    max_batch: int = 32
    linger_ms: float = 2.0
    # update coalescing: group verified UpdateRequests into micro-batches
    # submitted to the state machine (and folded) as one stacked dispatch
    coalesce: bool = True
    coalesce_max_batch: int = 32
    coalesce_linger_ms: float = 2.0
    # Retry-After floor handed to shed clients (seconds)
    retry_after_seconds: float = 1.0
    # upload wire format advertised in the round params: "legacy" keeps the
    # v1 interleaved element blocks, "packed" advertises the v2 byte-planar
    # layout (core.mask.serialization.WIRE_PLANAR_FLAG). The server parse
    # auto-detects per message, so either setting ACCEPTS both formats —
    # this only steers what well-behaved participants send.
    wire_format: str = "legacy"

    def validate(self) -> None:
        if self.wire_format not in ("legacy", "packed"):
            raise SettingsError("ingest.wire_format must be legacy | packed")
        if self.shards < 1:
            raise SettingsError("ingest.shards must be >= 1")
        if self.queue_bound < 1:
            raise SettingsError("ingest.queue_bound must be >= 1")
        if not (0.0 < self.low_watermark <= self.high_watermark <= 1.0):
            raise SettingsError(
                "ingest watermarks must satisfy 0 < low <= high <= 1"
            )
        if self.max_batch < 1 or self.coalesce_max_batch < 1:
            raise SettingsError("ingest batch sizes must be >= 1")
        if self.linger_ms < 0 or self.coalesce_linger_ms < 0:
            raise SettingsError("ingest linger must be >= 0")
        if self.retry_after_seconds <= 0:
            raise SettingsError("ingest.retry_after_seconds must be > 0")


@dataclass
class LoadgenSettings:
    """Sim-fed load generation (``xaynet_tpu.loadgen``, docs/DESIGN.md §21).

    Consumed by the loadgen runner / bench harness, not the coordinator —
    it lives in the same TOML so one config file describes a whole soak
    (coordinator + traffic source), like ``[edge]`` does for the edge tier.
    """

    participants: int = 2000  # simulated update participants per round
    drivers: int = 1  # process-sharded replay drivers (participant ranges)
    block_size: int = 512  # participants per jitted population block
    tenants: str = ""  # csv tenant ids to spread across ("" = root routes)
    wire: str = "auto"  # auto (follow round params) | packed | legacy
    sum_participants: int = 1  # seed-dict width (sum-task population)
    dropout_rate: float = 0.0  # fraction that never uploads
    stragglers: int = 0  # participants delayed by straggle_delay_ms
    straggle_delay_ms: float = 0.0
    concurrency: int = 64  # in-flight uploads per driver
    seed: int = 1  # churn/arrival schedule seed

    def validate(self) -> None:
        if self.participants < 1:
            raise SettingsError("loadgen.participants must be >= 1")
        if self.drivers < 1:
            raise SettingsError("loadgen.drivers must be >= 1")
        if self.block_size < 1:
            raise SettingsError("loadgen.block_size must be >= 1")
        if self.wire not in ("auto", "packed", "legacy"):
            raise SettingsError("loadgen.wire must be auto | packed | legacy")
        if self.sum_participants < 1:
            raise SettingsError("loadgen.sum_participants must be >= 1")
        if not (0.0 <= self.dropout_rate < 1.0):
            raise SettingsError("loadgen.dropout_rate must be in [0, 1)")
        if self.stragglers < 0 or self.straggle_delay_ms < 0:
            raise SettingsError("loadgen straggler settings must be >= 0")
        if self.concurrency < 1:
            raise SettingsError("loadgen.concurrency must be >= 1")


@dataclass
class ResilienceSettings:
    """Retry/breaker policy for storage calls, mid-round checkpoints, and
    fault injection (``xaynet_tpu.resilience``).

    Defaults are safe for every deployment: transient storage faults retry
    in place with bounded backoff, the breaker stops retry pile-ups during
    a real outage, and checkpointing/fault-injection stay off until
    explicitly enabled.
    """

    enabled: bool = True  # wrap the store in retry + circuit breaker
    # retry policy (decorrelated jitter): attempts counts calls, so 1 = no
    # retry; the deadline caps total in-place blocking per storage call
    retry_max_attempts: int = 4
    retry_base_ms: float = 25.0
    retry_max_ms: float = 2000.0
    retry_deadline_s: float = 30.0
    # circuit breaker: consecutive failures before fail-fast, seconds until
    # the half-open probe window, concurrent half-open probes allowed
    breaker_threshold: int = 5
    breaker_reset_s: float = 10.0
    breaker_half_open_max: int = 1
    # durable mid-round aggregate checkpoints (update phase): persist every
    # N fold batches or T seconds, whichever comes first; 0 disables the
    # time trigger
    checkpoint_enabled: bool = False
    checkpoint_every_batches: int = 8
    checkpoint_every_s: float = 30.0
    # Failure-phase round resume: how many times one round may re-enter
    # Update from its checkpoint before falling back to a round restart
    max_resume_attempts: int = 2
    # deterministic fault plan spec ("" = off); see resilience.faults
    fault_plan: str = ""

    def validate(self) -> None:
        if self.retry_max_attempts < 1:
            raise SettingsError("resilience.retry_max_attempts must be >= 1")
        if self.retry_base_ms <= 0 or self.retry_max_ms < self.retry_base_ms:
            raise SettingsError("resilience retry delays need 0 < base <= max")
        if self.retry_deadline_s <= 0:
            raise SettingsError("resilience.retry_deadline_s must be > 0")
        if self.breaker_threshold < 1:
            raise SettingsError("resilience.breaker_threshold must be >= 1")
        if self.breaker_reset_s <= 0:
            raise SettingsError("resilience.breaker_reset_s must be > 0")
        if self.breaker_half_open_max < 1:
            raise SettingsError("resilience.breaker_half_open_max must be >= 1")
        if self.checkpoint_every_batches < 1:
            raise SettingsError("resilience.checkpoint_every_batches must be >= 1")
        if self.checkpoint_every_s < 0:
            raise SettingsError("resilience.checkpoint_every_s must be >= 0")
        if self.max_resume_attempts < 0:
            raise SettingsError("resilience.max_resume_attempts must be >= 0")
        if self.fault_plan:
            from ..resilience.faults import FaultPlan

            try:
                FaultPlan.parse(self.fault_plan)
            except ValueError as e:
                raise SettingsError(f"resilience.fault_plan: {e}") from e


@dataclass
class LivenessSettings:
    """Round liveness under participant churn (docs/DESIGN.md §10).

    Two independent mechanisms: quorum completion (a stalled phase with
    ``accepted >= count.quorum`` closes DEGRADED instead of timing out —
    armed per phase by setting ``pet.<phase>.count.quorum``), and the
    adaptive :class:`~xaynet_tpu.server.round_controller.RoundController`
    (off by default) that re-sizes ``count.min``/``time.max`` across rounds
    with hysteresis when the offered participant load does not match the
    configured window.
    """

    # quorum completion: after time.min, a phase at/above quorum closes
    # degraded once no message has been ACCEPTED for this many seconds
    stall_grace_s: float = 5.0
    # adaptive count windows (RoundController)
    adaptive: bool = False
    shrink_after: int = 2  # consecutive degraded/failed rounds before a shrink
    grow_after: int = 2  # consecutive full rounds before a regrow
    shrink_factor: float = 0.5  # count.min multiplier on shrink (then clamped
    # down to the arrivals actually observed, and up to the protocol floor)
    grow_factor: float = 1.5  # count.min multiplier on regrow (capped at the
    # configured min and the observed arrivals)
    time_relax_factor: float = 1.5  # time.max multiplier on shrink; regrows
    # decay it back toward the configured value
    time_max_ceil_s: float = 3600.0  # absolute ceiling for relaxed time.max
    window: int = 8  # rounds of per-phase arrival history kept

    def validate(self) -> None:
        if self.stall_grace_s <= 0:
            raise SettingsError("liveness.stall_grace_s must be > 0")
        if self.shrink_after < 1 or self.grow_after < 1:
            raise SettingsError("liveness shrink_after/grow_after must be >= 1")
        if not (0.0 < self.shrink_factor < 1.0):
            raise SettingsError("liveness.shrink_factor must be in (0, 1)")
        if self.grow_factor <= 1.0:
            raise SettingsError("liveness.grow_factor must be > 1")
        if self.time_relax_factor < 1.0:
            raise SettingsError("liveness.time_relax_factor must be >= 1")
        if self.time_max_ceil_s <= 0:
            raise SettingsError("liveness.time_max_ceil_s must be > 0")
        if self.window < 1:
            raise SettingsError("liveness.window must be >= 1")


@dataclass
class EdgeSettings:
    """Hierarchical edge pre-aggregation tier (``xaynet_tpu.edge``,
    docs/DESIGN.md §11). One section, two roles:

    - on the COORDINATOR, ``enabled = true`` serves the edge endpoints
      (``GET /edge/round`` — round params + round keys for the trusted
      edge tier, ``POST /edge/envelope`` — partial-aggregate intake);
    - on an EDGE process (``python -m xaynet_tpu.edge.runner``),
      ``upstream_url`` names the coordinator and the window knobs bound
      how much an edge batches before shipping one envelope upstream.

    ``token``, when set on both sides, must match (``X-Edge-Token``) —
    edges sit inside the coordinator's trust domain (they decrypt
    participant uploads with the round keys), so the endpoint is never
    served to anonymous callers unless the operator explicitly leaves the
    token empty on a closed network.
    """

    enabled: bool = False  # coordinator: serve /edge/round + /edge/envelope
    token: str = ""  # shared secret for the edge endpoints ("" = open)
    # edge-runner role
    upstream_url: str = ""  # coordinator base URL (required for the runner)
    edge_id: str = ""  # stable identity; "" derives host:port at startup
    max_members: int = 64  # seal the window at this many folded updates
    linger_s: float = 0.5  # seal a non-empty window after this much time
    poll_s: float = 0.25  # upstream round/phase poll cadence

    def validate(self) -> None:
        if self.max_members < 1:
            raise SettingsError("edge.max_members must be >= 1")
        if self.linger_s < 0:
            raise SettingsError("edge.linger_s must be >= 0")
        if self.poll_s <= 0:
            raise SettingsError("edge.poll_s must be > 0")

    def validate_runner(self) -> None:
        """Extra invariants for the edge runner entrypoint."""
        self.validate()
        if not self.upstream_url:
            raise SettingsError("edge.upstream_url is required to run an edge")


@dataclass
class TenancySettings:
    """``[tenancy]`` — multi-tenant coordinator over the paged accumulator
    pool (docs/DESIGN.md §19).

    With ``enabled = true`` one coordinator process runs one full round
    pipeline per id in ``tenants`` — each with its own mask config, model
    length and liveness policy (per-tenant override TOML in
    ``config_dir/<tenant>.toml``, loaded through the normal settings
    loader) — sharing the mesh, the page pool and the REST listener. The
    FIRST id doubles as the default tenant serving the bare legacy routes;
    every tenant is also reachable under ``/t/<tenant>/...``.

    Pool knobs size the shared arena (pages of ``page_kib`` KiB; 0 caps =
    uncapped, the host arena grows by ``slab_pages``-page slabs);
    ``max_inflight_folds`` bounds fold batches in flight across ALL
    tenants (the scheduler's backpressure); ``ingest_capacity`` and
    ``max_share`` shape the per-tenant admission budget layered on each
    tenant's AdmissionController.
    """

    enabled: bool = False
    tenants: list = field(default_factory=list)  # validated tenant ids
    config_dir: str = ""  # per-tenant override TOMLs: <dir>/<tenant>.toml
    page_kib: int = 1024  # pool page size (multiple of 4 KiB)
    slab_pages: int = 64  # host-arena growth granularity
    host_pages: int = 0  # 0 = uncapped
    device_pages: int = 0  # 0 = uncapped
    max_inflight_folds: int = 8  # cross-tenant fold-batch bound
    ingest_capacity: int = 4096  # process-wide admission budget (messages)
    max_share: float = 0.6  # one tenant's ceiling of that budget
    # -- elastic lifecycle (docs/DESIGN.md §23) -----------------------------
    admin_token: str = ""  # "" disables /admin/tenants entirely
    drain_timeout_s: float = 120.0  # graceful-drain budget before hard kill
    quarantine_failures: int = 3  # consecutive round failures tripping it
    quarantine_reset_s: float = 60.0  # open -> half-open probe delay
    defrag_enabled: bool = True  # between-round host-arena compaction
    defrag_threshold: float = 0.5  # fragmentation tripping a compaction
    weights: str = ""  # "tenant=weight,..." fair-share weights
    tiers: str = ""  # "tenant=tier,..." priority tiers (lower wins)

    def tenant_weights(self) -> dict:
        """Parsed ``weights``: ``{tenant: weight}`` (same string form as
        ``slo.tenant_round_wall_s`` — env-overridable, mini-TOML-safe)."""
        return {
            t: float(v) for t, v in _parse_tenant_pairs(self.weights)
        }

    def tenant_tiers(self) -> dict:
        """Parsed ``tiers``: ``{tenant: tier}`` (lower tier wins slots)."""
        return {t: int(float(v)) for t, v in _parse_tenant_pairs(self.tiers)}

    def validate(self) -> None:
        from ..tenancy.registry import validate_tenant_id

        if self.enabled and not self.tenants:
            raise SettingsError("tenancy.enabled requires at least one tenant id")
        seen = set()
        for tid in self.tenants:
            try:
                validate_tenant_id(str(tid))
            except ValueError as e:
                raise SettingsError(f"tenancy.tenants: {e}") from e
            if tid in seen:
                raise SettingsError(f"tenancy.tenants: duplicate id {tid!r}")
            seen.add(tid)
        if self.page_kib < 4 or self.page_kib % 4:
            raise SettingsError("tenancy.page_kib must be a multiple of 4 (>= 4)")
        if self.slab_pages < 1:
            raise SettingsError("tenancy.slab_pages must be >= 1")
        if self.host_pages < 0 or self.device_pages < 0:
            raise SettingsError("tenancy.host_pages/device_pages must be >= 0")
        if self.max_inflight_folds < 1:
            raise SettingsError("tenancy.max_inflight_folds must be >= 1")
        if self.ingest_capacity < 1:
            raise SettingsError("tenancy.ingest_capacity must be >= 1")
        if not (0.0 < self.max_share <= 1.0):
            raise SettingsError("tenancy.max_share must be in (0, 1]")
        if self.drain_timeout_s <= 0:
            raise SettingsError("tenancy.drain_timeout_s must be > 0")
        if self.quarantine_failures < 1:
            raise SettingsError("tenancy.quarantine_failures must be >= 1")
        if self.quarantine_reset_s <= 0:
            raise SettingsError("tenancy.quarantine_reset_s must be > 0")
        if not (0.0 < self.defrag_threshold <= 1.0):
            raise SettingsError("tenancy.defrag_threshold must be in (0, 1]")
        try:
            weights = self.tenant_weights()
        except ValueError as e:
            raise SettingsError("tenancy.weights must be 'tenant=weight,...'") from e
        for tenant, weight in weights.items():
            if not tenant or weight <= 0:
                raise SettingsError(
                    "tenancy.weights entries need a tenant id and a positive weight"
                )
        try:
            self.tenant_tiers()
        except ValueError as e:
            raise SettingsError("tenancy.tiers must be 'tenant=tier,...'") from e


def _parse_tenant_pairs(spec: str) -> list:
    """Split a ``tenant=value,tenant=value`` string into pairs (shared by
    the tenancy weight/tier parsers and kept string-typed at the settings
    layer for env-override compatibility)."""
    out = []
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        tenant, _, value = pair.partition("=")
        out.append((tenant.strip(), value.strip()))
    return out


@dataclass
class SloSettings:
    """``[slo]`` — per-tenant SLO targets and burn-rate alerting
    (``telemetry.slo``, docs/DESIGN.md §20).

    ``round_wall_s`` is the round-wall target every tenant inherits;
    ``tenant_round_wall_s`` overrides it per tenant as a comma-separated
    ``tenant=seconds`` string (strings keep the section env-overridable
    and mini-TOML-parseable, like ``tenancy.tenants``). The three budgets
    are the allowed BAD fractions (slow rounds / degraded rounds / shed
    ingress); burn rate 1.0 means spending exactly that budget. An alert
    needs BOTH the fast and the slow window burning — ``warn`` at
    ``warn_burn``, ``page`` at ``page_burn`` (a page also drops a flight
    bundle, trigger ``slo-page``).
    """

    enabled: bool = True
    round_wall_s: float = 600.0  # default per-round wall target
    tenant_round_wall_s: str = ""  # "tenant=seconds,..." overrides
    round_wall_budget: float = 0.05  # allowed fraction of slow rounds
    degraded_budget: float = 0.1  # allowed fraction of degraded rounds
    shed_budget: float = 0.05  # allowed shed fraction of admissions
    fast_window_s: float = 300.0  # prompt-detection window
    slow_window_s: float = 3600.0  # spike-suppression window
    warn_burn: float = 6.0  # burn rate tripping warn
    page_burn: float = 14.4  # burn rate tripping page (+ flight dump)

    def tenant_targets(self) -> dict:
        """The parsed per-tenant overrides: ``{tenant: seconds}``."""
        out: dict[str, float] = {}
        for pair in self.tenant_round_wall_s.split(","):
            pair = pair.strip()
            if not pair:
                continue
            tenant, _, seconds = pair.partition("=")
            out[tenant.strip()] = float(seconds)
        return out

    def validate(self) -> None:
        if self.round_wall_s <= 0:
            raise SettingsError("slo.round_wall_s must be > 0")
        try:
            targets = self.tenant_targets()
        except ValueError as e:
            raise SettingsError(
                "slo.tenant_round_wall_s must be 'tenant=seconds,...'"
            ) from e
        for tenant, seconds in targets.items():
            if not tenant or seconds <= 0:
                raise SettingsError(
                    "slo.tenant_round_wall_s entries need a tenant id and a "
                    "positive target"
                )
        for name in ("round_wall_budget", "degraded_budget", "shed_budget"):
            if not (0.0 < getattr(self, name) <= 1.0):
                raise SettingsError(f"slo.{name} must be in (0, 1]")
        if self.fast_window_s <= 0 or self.slow_window_s <= 0:
            raise SettingsError("slo windows must be > 0")
        if self.fast_window_s > self.slow_window_s:
            raise SettingsError("slo.fast_window_s must be <= slow_window_s")
        if self.warn_burn <= 0 or self.page_burn < self.warn_burn:
            raise SettingsError("slo burn thresholds need 0 < warn_burn <= page_burn")


@dataclass
class OverlapSettings:
    """``[overlap]`` — round-phase overlap & speculation (docs/DESIGN.md §22).

    The PET phase chain is serial by protocol, not by data dependency:
    the sum2 mask derivation needs only the sealed sum dict, the fold
    drain needs only staged updates, and each shard's unmask slice needs
    only that shard's folds. Each flag opts one overlap out independently
    (the ``[liveness]`` idiom — mechanisms are orthogonal); ``enabled =
    false`` forces the fully serial pre-overlap behaviour regardless of
    the per-feature flags. Every overlap is byte-identity preserving: a
    disabled or mis-speculated fast path falls back to the on-demand
    serial path.
    """

    enabled: bool = True
    # derive sum2 masks speculatively during the update phase (bench/sim
    # rounds where the sum participant is in-process); mis-speculated
    # seeds are discarded by an exact modular subtract
    speculative_derive: bool = True
    # subtract each shard's mask slice as soon as ITS last fold commits
    # at the drain barrier (instead of global drain + a separate pass)
    eager_unmask: bool = True
    # let the update-phase fold drain ride into the sum2 request window
    # instead of blocking the phase transition on it
    sum2_drain: bool = True
    # seeds per speculative derive group (bounds resident mask memory to
    # one accumulator + one group of per-seed derivations)
    spec_group: int = 8

    def feature(self, name: str) -> bool:
        """Effective per-feature switch (master ``enabled`` gates all)."""
        return self.enabled and bool(getattr(self, name))

    def validate(self) -> None:
        if self.spec_group < 1:
            raise SettingsError("overlap.spec_group must be >= 1")


@dataclass
class Settings:
    pet: PetSettings
    mask: MaskSettings = field(default_factory=MaskSettings)
    model: ModelSettings = field(default_factory=ModelSettings)
    api: ApiSettings = field(default_factory=ApiSettings)
    storage: StorageSettings = field(default_factory=StorageSettings)
    restore: RestoreSettings = field(default_factory=RestoreSettings)
    metrics: MetricsSettings = field(default_factory=MetricsSettings)
    log: LoggingSettings = field(default_factory=LoggingSettings)
    aggregation: AggregationSettings = field(default_factory=AggregationSettings)
    ingest: IngestSettings = field(default_factory=IngestSettings)
    resilience: ResilienceSettings = field(default_factory=ResilienceSettings)
    liveness: LivenessSettings = field(default_factory=LivenessSettings)
    edge: EdgeSettings = field(default_factory=EdgeSettings)
    tenancy: TenancySettings = field(default_factory=TenancySettings)
    slo: SloSettings = field(default_factory=SloSettings)
    loadgen: LoadgenSettings = field(default_factory=LoadgenSettings)
    overlap: OverlapSettings = field(default_factory=OverlapSettings)

    def validate(self) -> None:
        self.pet.validate()
        self.api.validate()
        self.tenancy.validate()
        self.slo.validate()
        self.overlap.validate()
        try:
            self.mask.to_config()  # quant level vs data/bound-type ceiling
        except ValueError as e:
            raise SettingsError(f"mask.quant: {e}") from e
        self.ingest.validate()
        self.loadgen.validate()
        self.resilience.validate()
        self.liveness.validate()
        self.edge.validate()
        if self.model.length < 1:
            raise SettingsError("model.length must be >= 1")
        if self.aggregation.batch_size < 1:
            raise SettingsError("aggregation.batch_size must be >= 1")
        if self.aggregation.dispatch_ahead < 1:
            raise SettingsError("aggregation.dispatch_ahead must be >= 1")
        if self.aggregation.staging_buffers < 2:
            raise SettingsError("aggregation.staging_buffers must be >= 2")
        if self.aggregation.kernel not in FOLD_KERNELS:
            raise SettingsError(
                "aggregation.kernel must be one of: " + " | ".join(FOLD_KERNELS)
            )
        if self.aggregation.wire_ingest and not self.aggregation.device:
            raise SettingsError("aggregation.wire_ingest requires aggregation.device = true")
        if self.aggregation.shard_threads < 0:
            raise SettingsError("aggregation.shard_threads must be >= 0 (0 = auto split)")
        if self.metrics.trace not in ("", "on", "failure", "off"):
            raise SettingsError(
                "metrics.trace must be on | failure | off (or omitted to "
                "defer to XAYNET_TRACE)"
            )

    @classmethod
    def default(cls) -> "Settings":
        return cls(
            pet=PetSettings(
                sum=PhaseSettings(
                    prob=0.01,
                    count=CountSettings(min=1, max=100),
                    time=TimeSettings(min=0.0, max=600.0),
                ),
                update=PhaseSettings(
                    prob=0.1,
                    count=CountSettings(min=3, max=10000),
                    time=TimeSettings(min=0.0, max=600.0),
                ),
                sum2=Sum2Settings(
                    count=CountSettings(min=1, max=100),
                    time=TimeSettings(min=0.0, max=600.0),
                ),
            )
        )

    @classmethod
    def load(cls, path: Optional[str] = None, env: Optional[dict] = None) -> "Settings":
        """Load from TOML (optional) with ``XAYNET__SECTION__KEY`` env overrides."""
        raw: dict[str, Any] = {}
        if path is not None:
            if tomllib is not None:
                with open(path, "rb") as f:
                    raw = tomllib.load(f)
            else:
                with open(path, "r", encoding="utf-8") as f:
                    raw = _mini_toml(f.read())
        env = dict(os.environ if env is None else env)
        for key, value in env.items():
            if not key.startswith("XAYNET__"):
                continue
            parts = [p.lower() for p in key.split("__")[1:]]
            node = raw
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = _coerce(value)
        settings = cls._from_raw(raw)
        settings.validate()
        return settings

    @classmethod
    def _from_raw(cls, raw: dict) -> "Settings":
        base = cls.default()
        pet = raw.get("pet", {})

        def phase(name: str, default: PhaseSettings | Sum2Settings):
            section = pet.get(name, {})
            count = section.get("count", {})
            time_ = section.get("time", {})
            quorum = count.get("quorum", default.count.quorum)
            kwargs = dict(
                count=CountSettings(
                    min=int(count.get("min", default.count.min)),
                    max=int(count.get("max", default.count.max)),
                    quorum=None if quorum is None else int(quorum),
                ),
                time=TimeSettings(
                    min=float(time_.get("min", default.time.min)),
                    max=float(time_.get("max", default.time.max)),
                ),
            )
            if isinstance(default, PhaseSettings):
                return PhaseSettings(prob=float(section.get("prob", default.prob)), **kwargs)
            return Sum2Settings(**kwargs)

        mask_raw = raw.get("mask", {})
        model_raw = raw.get("model", {})
        api_raw = raw.get("api", {})
        storage_raw = raw.get("storage", {})
        restore_raw = raw.get("restore", {})
        metrics_raw = raw.get("metrics", {})
        log_raw = raw.get("log", {})
        agg_raw = raw.get("aggregation", {})
        ingest_raw = raw.get("ingest", {})
        res_raw = raw.get("resilience", {})
        res_base = base.resilience
        live_raw = raw.get("liveness", {})
        live_base = base.liveness
        edge_raw = raw.get("edge", {})
        edge_base = base.edge
        ten_raw = raw.get("tenancy", {})
        ten_base = base.tenancy
        slo_raw = raw.get("slo", {})
        slo_base = base.slo
        lg_raw = raw.get("loadgen", {})
        lg_base = base.loadgen
        ov_raw = raw.get("overlap", {})
        ov_base = base.overlap

        return cls(
            pet=PetSettings(
                sum=phase("sum", base.pet.sum),
                update=phase("update", base.pet.update),
                sum2=phase("sum2", base.pet.sum2),
            ),
            mask=MaskSettings(
                group_type=_enum(GroupType, mask_raw.get("group_type", "prime")),
                data_type=_enum(DataType, mask_raw.get("data_type", "f32")),
                bound_type=_enum(BoundType, mask_raw.get("bound_type", "b0")),
                model_type=_enum(ModelType, mask_raw.get("model_type", "m3")),
                quant=int(mask_raw.get("quant", base.mask.quant)),
            ),
            model=ModelSettings(length=int(model_raw.get("length", base.model.length))),
            api=ApiSettings(
                bind_address=str(api_raw.get("bind_address", base.api.bind_address)),
                tls_certificate=api_raw.get("tls_certificate"),
                tls_key=api_raw.get("tls_key"),
                tls_client_auth=api_raw.get("tls_client_auth"),
            ),
            storage=StorageSettings(
                backend=str(storage_raw.get("backend", base.storage.backend)),
                model_dir=str(storage_raw.get("model_dir", base.storage.model_dir)),
                coordinator=str(storage_raw.get("coordinator", base.storage.coordinator)),
                redis_host=str(storage_raw.get("redis_host", base.storage.redis_host)),
                redis_port=int(storage_raw.get("redis_port", base.storage.redis_port)),
                redis_db=int(storage_raw.get("redis_db", base.storage.redis_db)),
                s3_endpoint=str(storage_raw.get("s3_endpoint", base.storage.s3_endpoint)),
                s3_bucket=str(storage_raw.get("s3_bucket", base.storage.s3_bucket)),
                s3_access_key=str(storage_raw.get("s3_access_key", base.storage.s3_access_key)),
                s3_secret_key=str(storage_raw.get("s3_secret_key", base.storage.s3_secret_key)),
                s3_region=str(storage_raw.get("s3_region", base.storage.s3_region)),
            ),
            restore=RestoreSettings(enable=bool(restore_raw.get("enable", False))),
            metrics=MetricsSettings(
                enable=bool(metrics_raw.get("enable", False)),
                sink=str(metrics_raw.get("sink", base.metrics.sink)),
                path=str(metrics_raw.get("path", base.metrics.path)),
                url=str(metrics_raw.get("url", base.metrics.url)),
                database=str(metrics_raw.get("database", base.metrics.database)),
                round_report_path=str(
                    metrics_raw.get("round_report_path", base.metrics.round_report_path)
                ),
                trace=str(metrics_raw.get("trace", base.metrics.trace)),
                trace_dir=str(metrics_raw.get("trace_dir", base.metrics.trace_dir)),
                flight_dir=str(metrics_raw.get("flight_dir", base.metrics.flight_dir)),
            ),
            log=LoggingSettings(filter=str(log_raw.get("filter", base.log.filter))),
            aggregation=AggregationSettings(
                device=bool(agg_raw.get("device", False)),
                batch_size=int(agg_raw.get("batch_size", base.aggregation.batch_size)),
                kernel=str(agg_raw.get("kernel", base.aggregation.kernel)),
                dispatch_ahead=int(
                    agg_raw.get("dispatch_ahead", base.aggregation.dispatch_ahead)
                ),
                staging_buffers=int(
                    agg_raw.get("staging_buffers", base.aggregation.staging_buffers)
                ),
                wire_ingest=bool(agg_raw.get("wire_ingest", base.aggregation.wire_ingest)),
                shard_parallel=bool(
                    agg_raw.get("shard_parallel", base.aggregation.shard_parallel)
                ),
                shard_threads=int(
                    agg_raw.get("shard_threads", base.aggregation.shard_threads)
                ),
                packed_staging=bool(
                    agg_raw.get("packed_staging", base.aggregation.packed_staging)
                ),
            ),
            ingest=IngestSettings(
                enabled=bool(ingest_raw.get("enabled", base.ingest.enabled)),
                shards=int(ingest_raw.get("shards", base.ingest.shards)),
                queue_bound=int(ingest_raw.get("queue_bound", base.ingest.queue_bound)),
                high_watermark=float(
                    ingest_raw.get("high_watermark", base.ingest.high_watermark)
                ),
                low_watermark=float(
                    ingest_raw.get("low_watermark", base.ingest.low_watermark)
                ),
                max_batch=int(ingest_raw.get("max_batch", base.ingest.max_batch)),
                linger_ms=float(ingest_raw.get("linger_ms", base.ingest.linger_ms)),
                coalesce=bool(ingest_raw.get("coalesce", base.ingest.coalesce)),
                coalesce_max_batch=int(
                    ingest_raw.get("coalesce_max_batch", base.ingest.coalesce_max_batch)
                ),
                coalesce_linger_ms=float(
                    ingest_raw.get("coalesce_linger_ms", base.ingest.coalesce_linger_ms)
                ),
                retry_after_seconds=float(
                    ingest_raw.get("retry_after_seconds", base.ingest.retry_after_seconds)
                ),
                wire_format=str(
                    ingest_raw.get("wire_format", base.ingest.wire_format)
                ),
            ),
            resilience=ResilienceSettings(
                enabled=bool(res_raw.get("enabled", res_base.enabled)),
                retry_max_attempts=int(
                    res_raw.get("retry_max_attempts", res_base.retry_max_attempts)
                ),
                retry_base_ms=float(res_raw.get("retry_base_ms", res_base.retry_base_ms)),
                retry_max_ms=float(res_raw.get("retry_max_ms", res_base.retry_max_ms)),
                retry_deadline_s=float(
                    res_raw.get("retry_deadline_s", res_base.retry_deadline_s)
                ),
                breaker_threshold=int(
                    res_raw.get("breaker_threshold", res_base.breaker_threshold)
                ),
                breaker_reset_s=float(
                    res_raw.get("breaker_reset_s", res_base.breaker_reset_s)
                ),
                breaker_half_open_max=int(
                    res_raw.get("breaker_half_open_max", res_base.breaker_half_open_max)
                ),
                checkpoint_enabled=bool(
                    res_raw.get("checkpoint_enabled", res_base.checkpoint_enabled)
                ),
                checkpoint_every_batches=int(
                    res_raw.get("checkpoint_every_batches", res_base.checkpoint_every_batches)
                ),
                checkpoint_every_s=float(
                    res_raw.get("checkpoint_every_s", res_base.checkpoint_every_s)
                ),
                max_resume_attempts=int(
                    res_raw.get("max_resume_attempts", res_base.max_resume_attempts)
                ),
                fault_plan=str(res_raw.get("fault_plan", res_base.fault_plan)),
            ),
            liveness=LivenessSettings(
                stall_grace_s=float(live_raw.get("stall_grace_s", live_base.stall_grace_s)),
                adaptive=bool(live_raw.get("adaptive", live_base.adaptive)),
                shrink_after=int(live_raw.get("shrink_after", live_base.shrink_after)),
                grow_after=int(live_raw.get("grow_after", live_base.grow_after)),
                shrink_factor=float(live_raw.get("shrink_factor", live_base.shrink_factor)),
                grow_factor=float(live_raw.get("grow_factor", live_base.grow_factor)),
                time_relax_factor=float(
                    live_raw.get("time_relax_factor", live_base.time_relax_factor)
                ),
                time_max_ceil_s=float(
                    live_raw.get("time_max_ceil_s", live_base.time_max_ceil_s)
                ),
                window=int(live_raw.get("window", live_base.window)),
            ),
            edge=EdgeSettings(
                enabled=bool(edge_raw.get("enabled", edge_base.enabled)),
                token=str(edge_raw.get("token", edge_base.token)),
                upstream_url=str(edge_raw.get("upstream_url", edge_base.upstream_url)),
                edge_id=str(edge_raw.get("edge_id", edge_base.edge_id)),
                max_members=int(edge_raw.get("max_members", edge_base.max_members)),
                linger_s=float(edge_raw.get("linger_s", edge_base.linger_s)),
                poll_s=float(edge_raw.get("poll_s", edge_base.poll_s)),
            ),
            tenancy=TenancySettings(
                enabled=bool(ten_raw.get("enabled", ten_base.enabled)),
                # a TOML array, or a comma-separated string (env overrides
                # and the mini-TOML fallback deliver strings)
                tenants=(
                    [t.strip() for t in ten_raw["tenants"].split(",") if t.strip()]
                    if isinstance(ten_raw.get("tenants"), str)
                    else [str(t) for t in ten_raw.get("tenants", ten_base.tenants)]
                ),
                config_dir=str(ten_raw.get("config_dir", ten_base.config_dir)),
                page_kib=int(ten_raw.get("page_kib", ten_base.page_kib)),
                slab_pages=int(ten_raw.get("slab_pages", ten_base.slab_pages)),
                host_pages=int(ten_raw.get("host_pages", ten_base.host_pages)),
                device_pages=int(ten_raw.get("device_pages", ten_base.device_pages)),
                max_inflight_folds=int(
                    ten_raw.get("max_inflight_folds", ten_base.max_inflight_folds)
                ),
                ingest_capacity=int(
                    ten_raw.get("ingest_capacity", ten_base.ingest_capacity)
                ),
                max_share=float(ten_raw.get("max_share", ten_base.max_share)),
                admin_token=str(ten_raw.get("admin_token", ten_base.admin_token)),
                drain_timeout_s=float(
                    ten_raw.get("drain_timeout_s", ten_base.drain_timeout_s)
                ),
                quarantine_failures=int(
                    ten_raw.get("quarantine_failures", ten_base.quarantine_failures)
                ),
                quarantine_reset_s=float(
                    ten_raw.get("quarantine_reset_s", ten_base.quarantine_reset_s)
                ),
                defrag_enabled=bool(
                    ten_raw.get("defrag_enabled", ten_base.defrag_enabled)
                ),
                defrag_threshold=float(
                    ten_raw.get("defrag_threshold", ten_base.defrag_threshold)
                ),
                weights=str(ten_raw.get("weights", ten_base.weights)),
                tiers=str(ten_raw.get("tiers", ten_base.tiers)),
            ),
            slo=SloSettings(
                enabled=bool(slo_raw.get("enabled", slo_base.enabled)),
                round_wall_s=float(slo_raw.get("round_wall_s", slo_base.round_wall_s)),
                tenant_round_wall_s=str(
                    slo_raw.get("tenant_round_wall_s", slo_base.tenant_round_wall_s)
                ),
                round_wall_budget=float(
                    slo_raw.get("round_wall_budget", slo_base.round_wall_budget)
                ),
                degraded_budget=float(
                    slo_raw.get("degraded_budget", slo_base.degraded_budget)
                ),
                shed_budget=float(slo_raw.get("shed_budget", slo_base.shed_budget)),
                fast_window_s=float(
                    slo_raw.get("fast_window_s", slo_base.fast_window_s)
                ),
                slow_window_s=float(
                    slo_raw.get("slow_window_s", slo_base.slow_window_s)
                ),
                warn_burn=float(slo_raw.get("warn_burn", slo_base.warn_burn)),
                page_burn=float(slo_raw.get("page_burn", slo_base.page_burn)),
            ),
            loadgen=LoadgenSettings(
                participants=int(lg_raw.get("participants", lg_base.participants)),
                drivers=int(lg_raw.get("drivers", lg_base.drivers)),
                block_size=int(lg_raw.get("block_size", lg_base.block_size)),
                tenants=str(lg_raw.get("tenants", lg_base.tenants)),
                wire=str(lg_raw.get("wire", lg_base.wire)),
                sum_participants=int(
                    lg_raw.get("sum_participants", lg_base.sum_participants)
                ),
                dropout_rate=float(lg_raw.get("dropout_rate", lg_base.dropout_rate)),
                stragglers=int(lg_raw.get("stragglers", lg_base.stragglers)),
                straggle_delay_ms=float(
                    lg_raw.get("straggle_delay_ms", lg_base.straggle_delay_ms)
                ),
                concurrency=int(lg_raw.get("concurrency", lg_base.concurrency)),
                seed=int(lg_raw.get("seed", lg_base.seed)),
            ),
            overlap=OverlapSettings(
                enabled=bool(ov_raw.get("enabled", ov_base.enabled)),
                speculative_derive=bool(
                    ov_raw.get("speculative_derive", ov_base.speculative_derive)
                ),
                eager_unmask=bool(ov_raw.get("eager_unmask", ov_base.eager_unmask)),
                sum2_drain=bool(ov_raw.get("sum2_drain", ov_base.sum2_drain)),
                spec_group=int(ov_raw.get("spec_group", ov_base.spec_group)),
            ),
        )


def _mini_toml(text: str) -> dict:
    """TOML-subset parser for Python < 3.11 (no ``tomllib``).

    Covers exactly what the coordinator configs use: ``[dotted.section]``
    headers, ``key = value`` with string/bool/int/float scalars, comments
    and blank lines. Anything fancier (arrays, inline tables, multi-line
    strings) raises — better a loud error than silently dropped settings.
    """
    root: dict[str, Any] = {}
    node = root
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if stripped.startswith("[") and stripped.endswith("]"):
            header = stripped[1:-1].strip()
            if header.startswith("[") or header.endswith("]"):
                raise SettingsError(
                    f"config line {lineno}: arrays of tables ({stripped!r}) are "
                    "not supported by the tomllib fallback parser"
                )
            node = root
            for part in header.split("."):
                node = node.setdefault(part.strip(), {})
            continue
        key, eq, value = stripped.partition("=")
        if not eq:
            raise SettingsError(f"config line {lineno}: expected 'key = value'")
        value = value.strip()
        # strip a trailing comment (quote-aware for string values)
        if value.startswith('"'):
            end = value.find('"', 1)
            if end < 0:
                raise SettingsError(f"config line {lineno}: unterminated string")
            trailing = value[end + 1 :].split("#", 1)[0].strip()
            if trailing:
                raise SettingsError(
                    f"config line {lineno}: unexpected content after string: {trailing!r}"
                )
            node[key.strip()] = value[1:end]
            continue
        value = value.split("#", 1)[0].strip()
        coerced = _coerce(value)  # same bool/int/float ladder as env overrides
        if isinstance(coerced, str):
            # unquoted non-scalar (array, inline table, bareword): loud error
            raise SettingsError(f"config line {lineno}: unsupported value {value!r}")
        node[key.strip()] = coerced
    return root


def _coerce(value: str):
    low = value.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(value)
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        pass
    return value


def _enum(enum_cls, name):
    if isinstance(name, enum_cls):
        return name
    try:
        if isinstance(name, int):
            return enum_cls(name)
        return enum_cls[str(name).upper()]
    except KeyError as e:
        raise SettingsError(f"invalid {enum_cls.__name__}: {name}") from e
