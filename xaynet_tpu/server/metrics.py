"""Metrics recorders.

Functional port of the reference's metrics subsystem (reference:
rust/xaynet-server/src/metrics/): eight measurements tagged with
(round_id, phase) — phase transitions, round counts, per-phase
accepted/rejected/discarded message counters, unique-mask totals — plus
free-form events for phase errors. Sinks: structured log lines or a JSONL
file (the line-protocol analogue; external collectors tail it).
"""

from __future__ import annotations

import json
import logging
import threading
import time

from ..telemetry.registry import get_registry

logger = logging.getLogger("xaynet.metrics")

# the dispatcher's own health, visible on GET /metrics: lines lost to
# backpressure, and backoff rounds against a down/slow sink
_DISPATCH_DROPPED = get_registry().counter(
    "xaynet_metrics_dispatcher_dropped_total",
    "Metric lines dropped by the Influx HTTP dispatcher (queue overflow or "
    "failed batches against a down sink).",
)
_DISPATCH_BACKOFF = get_registry().counter(
    "xaynet_metrics_dispatcher_backoff_total",
    "Backoff sleeps taken by the Influx HTTP dispatcher after a failed POST.",
)


class Metrics:
    """Recorder interface: the eight reference measurements dispatch to a
    sink's ``_emit``; the base sink is a no-op recorder."""

    def _emit(self, measurement: str, value, round_id: int, phase: str = "") -> None: ...

    def phase(self, round_id: int, phase: str) -> None:
        self._emit("phase", phase, round_id, phase)

    def round_total(self, round_id: int) -> None:
        self._emit("round_total_number", round_id, round_id)

    def message_accepted(self, round_id: int, phase: str) -> None:
        self._emit("message_accepted", 1, round_id, phase)

    def message_rejected(self, round_id: int, phase: str) -> None:
        self._emit("message_rejected", 1, round_id, phase)

    def message_discarded(self, round_id: int, phase: str) -> None:
        self._emit("message_discarded", 1, round_id, phase)

    def message_purged(self, round_id: int, phase: str) -> None:
        """A queued request rejected by the phase-end purge — NOT an
        in-window protocol reject (degraded closes purge every straggler;
        dashboards must be able to tell the two apart)."""
        self._emit("message_purged", 1, round_id, phase)

    def masks_total(self, round_id: int, count: int) -> None:
        self._emit("masks_total_number", count, round_id)

    def phase_duration(self, round_id: int, phase: str, seconds: float) -> None:
        self._emit("phase_duration_seconds", round(seconds, 4), round_id, phase)

    def event(self, round_id: int, kind: str, detail: str = "") -> None:
        self._emit("event_" + kind, detail, round_id)

    def close(self) -> None:
        """Flush/stop the sink; no-op for synchronous sinks."""


class LogMetrics(Metrics):
    def _emit(self, measurement: str, value, round_id: int, phase: str = "") -> None:
        logger.info("metric %s=%s round_id=%d phase=%s", measurement, value, round_id, phase)

    def event(self, round_id: int, kind: str, detail: str = "") -> None:
        logger.warning("event %s round_id=%d: %s", kind, round_id, detail)


class JsonlMetrics(Metrics):
    """Appends one JSON object per measurement (thread-safe)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def _emit(self, measurement: str, value, round_id: int, phase: str = "") -> None:
        record = {
            "ts": time.time(),
            "measurement": measurement,
            "value": value,
            "round_id": round_id,
        }
        if phase:
            record["phase"] = phase
        line = json.dumps(record)
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")


def _influx_line(measurement: str, value, round_id: int, phase: str = "") -> str:
    tags = f",round_id={round_id}"
    if phase:
        tags += f",phase={phase}"
    if isinstance(value, (int, float)):
        field = f"value={value}"
    else:
        # line protocol: backslash BEFORE quote, and no raw newlines (a bad
        # value must never invalidate the rest of a batch)
        escaped = (
            str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", " ")
        )
        field = f'value="{escaped}"'
    return f"xaynet_{measurement}{tags} {field} {int(time.time() * 1e9)}"


class InfluxLineMetrics(JsonlMetrics):
    """InfluxDB line-protocol sink (append to a file; telegraf/collectors
    tail it). Same eight measurements as the reference's Influx recorder."""

    def _emit(self, measurement: str, value, round_id: int, phase: str = "") -> None:
        line = _influx_line(measurement, value, round_id, phase)
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")


class InfluxHttpMetrics(Metrics):
    """Network dispatcher: line protocol pushed to an InfluxDB write endpoint
    over a dedicated background thread (reference:
    rust/xaynet-server/src/metrics/recorders/influxdb/dispatcher.rs).

    Backpressure contract: recording NEVER blocks the coordinator. Lines go
    into a bounded queue; when the sink falls behind and the queue fills,
    the oldest lines are dropped and counted (``dropped``) — the state
    machine's latency is never coupled to the metrics backend.
    """

    def __init__(
        self,
        url: str,
        database: str = "metrics",
        queue_size: int = 4096,
        batch_max: int = 256,
        flush_interval: float = 0.2,
    ):
        import queue as queue_mod

        self.url = url.rstrip("/") + f"/write?db={database}"
        self.dropped = 0
        self._queue: "queue_mod.Queue[str]" = queue_mod.Queue(maxsize=queue_size)
        self._batch_max = batch_max
        self._flush_interval = flush_interval
        self._stop = threading.Event()  # out-of-band: can't be lost to drops
        self._thread = threading.Thread(target=self._run, name="metrics-dispatch", daemon=True)
        self._thread.start()

    # --- dispatcher thread ----------------------------------------------

    def _run(self) -> None:
        import queue as queue_mod

        backoff = 0.1
        while True:
            lines: list[str] = []
            try:
                lines.append(self._queue.get(timeout=self._flush_interval))
            except queue_mod.Empty:
                if self._stop.is_set():
                    return  # closed and fully drained
                continue
            while len(lines) < self._batch_max:
                try:
                    lines.append(self._queue.get_nowait())
                except queue_mod.Empty:
                    break
            try:
                self._post(lines)
                backoff = 0.1
            except Exception:
                if self._stop.is_set():
                    return  # don't stall shutdown retrying a dead sink
                # sink down: drop this batch (bounded memory beats blocking)
                self.dropped += len(lines)
                _DISPATCH_DROPPED.inc(len(lines))
                _DISPATCH_BACKOFF.inc()
                time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    def _post(self, lines: list[str]) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.url, data=("\n".join(lines) + "\n").encode(), method="POST"
        )
        with urllib.request.urlopen(req, timeout=5):
            pass

    def close(self) -> None:
        """Stops the dispatcher after it drains whatever is queued."""
        self._stop.set()
        self._thread.join(timeout=10)

    # --- recording (non-blocking) ----------------------------------------

    def _emit(self, measurement: str, value, round_id: int, phase: str = "") -> None:
        import queue as queue_mod

        line = _influx_line(measurement, value, round_id, phase)
        try:
            self._queue.put_nowait(line)
            return
        except queue_mod.Full:
            pass
        # full: drop the OLDEST so fresh data survives; count every line
        # actually lost (the evicted one, and the new one if a concurrent
        # producer refills the freed slot before we take it)
        self.dropped += 1
        _DISPATCH_DROPPED.inc()
        try:
            self._queue.get_nowait()
        except queue_mod.Empty:
            pass
        try:
            self._queue.put_nowait(line)
        except queue_mod.Full:
            self.dropped += 1  # the new line was lost as well
            _DISPATCH_DROPPED.inc()
