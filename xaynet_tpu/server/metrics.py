"""Metrics recorders.

Functional port of the reference's metrics subsystem (reference:
rust/xaynet-server/src/metrics/): eight measurements tagged with
(round_id, phase) — phase transitions, round counts, per-phase
accepted/rejected/discarded message counters, unique-mask totals — plus
free-form events for phase errors. Sinks: structured log lines or a JSONL
file (the line-protocol analogue; external collectors tail it).
"""

from __future__ import annotations

import json
import logging
import threading
import time

logger = logging.getLogger("xaynet.metrics")


class Metrics:
    """Recorder interface (all methods are fire-and-forget)."""

    def phase(self, round_id: int, phase: str) -> None: ...

    def round_total(self, round_id: int) -> None: ...

    def message_accepted(self, round_id: int, phase: str) -> None: ...

    def message_rejected(self, round_id: int, phase: str) -> None: ...

    def message_discarded(self, round_id: int, phase: str) -> None: ...

    def masks_total(self, round_id: int, count: int) -> None: ...

    def phase_duration(self, round_id: int, phase: str, seconds: float) -> None: ...

    def event(self, round_id: int, kind: str, detail: str = "") -> None: ...


class LogMetrics(Metrics):
    def _emit(self, measurement: str, value, round_id: int, phase: str = "") -> None:
        logger.info("metric %s=%s round_id=%d phase=%s", measurement, value, round_id, phase)

    def phase(self, round_id: int, phase: str) -> None:
        self._emit("phase", phase, round_id, phase)

    def round_total(self, round_id: int) -> None:
        self._emit("round_total_number", round_id, round_id)

    def message_accepted(self, round_id: int, phase: str) -> None:
        self._emit("message_accepted", 1, round_id, phase)

    def message_rejected(self, round_id: int, phase: str) -> None:
        self._emit("message_rejected", 1, round_id, phase)

    def message_discarded(self, round_id: int, phase: str) -> None:
        self._emit("message_discarded", 1, round_id, phase)

    def masks_total(self, round_id: int, count: int) -> None:
        self._emit("masks_total_number", count, round_id)

    def phase_duration(self, round_id: int, phase: str, seconds: float) -> None:
        self._emit("phase_duration_seconds", round(seconds, 4), round_id, phase)

    def event(self, round_id: int, kind: str, detail: str = "") -> None:
        logger.warning("event %s round_id=%d: %s", kind, round_id, detail)


class JsonlMetrics(Metrics):
    """Appends one JSON object per measurement (thread-safe)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def _emit(self, measurement: str, value, round_id: int, phase: str = "") -> None:
        record = {
            "ts": time.time(),
            "measurement": measurement,
            "value": value,
            "round_id": round_id,
        }
        if phase:
            record["phase"] = phase
        line = json.dumps(record)
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")

    def phase(self, round_id: int, phase: str) -> None:
        self._emit("phase", phase, round_id, phase)

    def round_total(self, round_id: int) -> None:
        self._emit("round_total_number", round_id, round_id)

    def message_accepted(self, round_id: int, phase: str) -> None:
        self._emit("message_accepted", 1, round_id, phase)

    def message_rejected(self, round_id: int, phase: str) -> None:
        self._emit("message_rejected", 1, round_id, phase)

    def message_discarded(self, round_id: int, phase: str) -> None:
        self._emit("message_discarded", 1, round_id, phase)

    def masks_total(self, round_id: int, count: int) -> None:
        self._emit("masks_total_number", count, round_id)

    def phase_duration(self, round_id: int, phase: str, seconds: float) -> None:
        self._emit("phase_duration_seconds", round(seconds, 4), round_id, phase)

    def event(self, round_id: int, kind: str, detail: str = "") -> None:
        self._emit("event_" + kind, detail, round_id)


class InfluxLineMetrics(JsonlMetrics):
    """InfluxDB line-protocol sink (append to a file; telegraf/collectors
    tail it). Same eight measurements as the reference's Influx recorder."""

    def _emit(self, measurement: str, value, round_id: int, phase: str = "") -> None:
        tags = f",round_id={round_id}"
        if phase:
            tags += f",phase={phase}"
        if isinstance(value, (int, float)):
            field = f"value={value}"
        else:
            escaped = str(value).replace('"', '\\"')
            field = f'value="{escaped}"'
        line = f"xaynet_{measurement}{tags} {field} {int(time.time() * 1e9)}"
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")
