"""Update-phase aggregation strategies: host numpy or TPU mesh.

The reference aggregates each accepted update inline with a sequential
big-int loop (reference:
rust/xaynet-server/src/state_machine/phases/update.rs:119-152). Here updates
are staged and folded in batches:

- **host**: vectorized numpy limb kernels (``core.mask.Aggregation``);
- **device**: the sharded single-pass fold on the TPU mesh
  (``parallel.ShardedAggregator``) for the vector part, host for the tiny
  unit part.

Validation still happens per-update at accept time (the client-visible
protocol behavior is unchanged); only the arithmetic is deferred into
batches.
"""

from __future__ import annotations

import numpy as np

from ..core.mask.config import MaskConfigPair
from ..core.mask.masking import Aggregation, AggregationError
from ..core.mask.object import LazyWireMaskVect, MaskObject, MaskUnit, MaskVect
from ..telemetry import profiling


class StagedAggregator:
    """Stages validated masked updates and folds them in batches."""

    def __init__(
        self,
        config: MaskConfigPair,
        object_size: int,
        device: bool = False,
        batch_size: int = 64,
        ingest_workers: int = 4,
        mesh=None,
        kernel: str = "auto",
    ):
        self.config = config
        self.object_size = object_size
        self.batch_size = max(1, batch_size)
        self._staged_vect: list = []  # device: futures of planar arrays
        self._staged_unit: list[np.ndarray] = []
        self._count = 0
        self._host = Aggregation(config, object_size)
        self._device = None
        self._ingest_pool = None
        if device:
            from concurrent.futures import ThreadPoolExecutor

            from ..ops import limbs as limb_ops
            from ..parallel.aggregator import ShardedAggregator

            self._device = ShardedAggregator(config.vect, object_size, mesh=mesh, kernel=kernel)
            # tiny unit part stays on host
            self._unit_acc = np.zeros(
                limb_ops.n_limbs_for_order(config.unit.order), dtype=np.uint32
            )
            # wire->planar transposes overlap across workers: at 25M params
            # each update is a ~200MB relayout, which would serialize the
            # ingest path if done at flush time on one thread
            self._ingest_pool = ThreadPoolExecutor(
                max_workers=max(1, ingest_workers), thread_name_prefix="xn-ingest"
            )

    @property
    def kernel_used(self) -> str:
        """Which fold kernel actually ran (``host`` off-device; on device the
        resolved choice, or the configured one before the first fold)."""
        if self._device is None:
            return "host"
        return self._device.kernel_used or self._device.kernel

    @property
    def nb_models(self) -> int:
        return self._count + (self._device.nb_models if self._device else self._host.nb_models)

    def validate_aggregation(self, obj: MaskObject) -> None:
        """Per-update protocol validation (same checks as the reference,
        masking.rs:253-279) without materializing a probe accumulator."""
        if self.config.vect != obj.vect.config:
            raise AggregationError("ModelMismatch")
        if self.config.unit != obj.unit.config:
            raise AggregationError("ScalarMismatch")
        if self.object_size != len(obj.vect):
            raise AggregationError("ModelMismatch")
        if self.nb_models >= self.config.vect.max_nb_models:
            raise AggregationError("TooManyModels")
        if self.nb_models >= self.config.unit.max_nb_models:
            raise AggregationError("TooManyScalars")
        vect = obj.vect
        if (
            self._device is not None
            and isinstance(vect, LazyWireMaskVect)
            and not vect.materialized
        ):
            # device wire ingest: unpack + element validity run on the
            # accelerator, and the resulting planar is cached on the object
            # so stage() never re-uploads. Ordering is preserved — this runs
            # before the caller's seed-dict insert (update.rs:119-152).
            planar = self._device.validate_wire_update(np.asarray(vect.wire_block))
            if planar is None or not obj.unit.is_valid():
                raise AggregationError("InvalidObject")
            vect._staged_planar = planar
        elif not obj.is_valid():
            raise AggregationError("InvalidObject")

    @property
    def pending(self) -> int:
        """Updates staged but not yet folded."""
        return self._count

    def stage(self, obj: MaskObject) -> None:
        """Stage an update without folding (caller controls flush timing)."""
        if self._ingest_pool is not None:
            planar_dev = (
                obj.vect._staged_planar if isinstance(obj.vect, LazyWireMaskVect) else None
            )
            if planar_dev is not None:
                # wire ingest: validate_aggregation already unpacked this
                # update on device — stage the device-resident planar
                self._staged_vect.append(planar_dev)
            else:
                from ..ops.fold_jax import wire_to_planar

                padded = self._device.padded_length

                def to_planar(data=obj.vect.data):
                    planar = wire_to_planar(data)
                    if planar.shape[1] != padded:
                        planar = np.pad(planar, ((0, 0), (0, padded - planar.shape[1])))
                    return planar

                self._staged_vect.append(self._ingest_pool.submit(to_planar))
        else:
            self._staged_vect.append(obj.vect.data)
        self._staged_unit.append(obj.unit.data)
        self._count += 1

    def aggregate(self, obj: MaskObject) -> None:
        self.stage(obj)
        if self._count >= self.batch_size:
            self.flush()

    def flush(self) -> None:
        if self._count == 0:
            return
        stack = None if self._ingest_pool is not None else np.stack(self._staged_vect)
        units = np.stack(self._staged_unit)
        if self._device is not None:
            import jax
            import jax.numpy as jnp

            from ..ops import limbs as limb_ops

            parts = [p.result() if hasattr(p, "result") else p for p in self._staged_vect]
            self._staged_vect.clear()  # consume destructively: free as we fold
            if all(isinstance(p, jax.Array) for p in parts):
                # wire ingest: every planar is already device-resident and
                # validity-checked. Stack + fold in CHUNKS, dropping each
                # consumed reference, so peak HBM stays at the staged
                # planars + one chunk-sized copy instead of + a full second
                # batch (at 25M/batch 64 that difference is ~13 GB)
                chunk = 8
                while parts:
                    piece, parts = parts[:chunk], parts[chunk:]
                    staged_batch = jax.device_put(
                        jnp.stack(piece), self._device._batch_sharding
                    )
                    del piece
                    self._device.add_planar_batch(staged_batch)
            else:
                staged_batch = jax.device_put(
                    np.stack([np.asarray(p) for p in parts]), self._device._batch_sharding
                )
                self._device.add_planar_batch(staged_batch)
            order_limbs = limb_ops.order_limbs_for(self.config.unit.order)
            batch_unit = limb_ops.batch_mod_sum(units[:, None, :], order_limbs)[0]
            self._unit_acc = limb_ops.mod_add(
                self._unit_acc[None, :], batch_unit[None, :], order_limbs
            )[0]
        else:
            # same op label as the device fold: one /metrics series answers
            # "how fast is the masked add", whichever backend ran it
            profiling.timed_kernel(
                "masked_add",
                stack.shape[0] * self.object_size,
                lambda: self._host.aggregate_batch(stack, units),
            )
        self._staged_vect.clear()
        self._staged_unit.clear()
        self._count = 0

    def finalize(self) -> Aggregation:
        """Materialize the protocol-level ``Aggregation`` (for Unmask)."""
        self.flush()
        if self._device is None:
            return self._host
        agg = Aggregation(self.config, self.object_size)
        agg.object = MaskObject(
            MaskVect(self.config.vect, self._device.snapshot()),
            MaskUnit(self.config.unit, self._unit_acc),
        )
        agg.nb_models = self._device.nb_models
        return agg
