"""Update-phase aggregation strategies: host numpy or TPU mesh.

The reference aggregates each accepted update inline with a sequential
big-int loop (reference:
rust/xaynet-server/src/state_machine/phases/update.rs:119-152). Here updates
are staged and folded in batches:

- **host**: vectorized numpy limb kernels (``core.mask.Aggregation``);
- **device**: the sharded single-pass fold on the TPU mesh
  (``parallel.ShardedAggregator``) for the vector part, host for the tiny
  unit part. Device folds flow through the streaming pipeline
  (``parallel.streaming``): ``flush()`` *submits* the staged micro-batch
  into a bounded producer/consumer (ring-buffer staging overlaps the
  in-flight folds) and ``drain()`` — called at phase end and in
  ``finalize`` — blocks for the result. The fold math is an exact modular
  sum, so the aggregate is byte-identical to the synchronous path.

Validation still happens per-update at accept time (the client-visible
protocol behavior is unchanged); only the arithmetic is deferred into
batches.
"""

from __future__ import annotations

import numpy as np

from ..core.mask.config import MaskConfigPair
from ..core.mask.masking import Aggregation, AggregationError, UnmaskingError
from ..core.mask.object import LazyWireMaskVect, MaskObject, MaskUnit, MaskVect
from ..ops import limbs as limb_ops
from ..resilience.checkpoint import AggSnapshot
from ..telemetry import profiling


def build_staged_aggregator(shared) -> "StagedAggregator":
    """The ONE way a phase builds the round's aggregator from settings —
    shared by the update phase's normal entry and the journal-resume
    factories re-entering sum2/unmask (docs/DESIGN.md §9), so a resumed
    round folds and unmasks with exactly the configuration it crashed
    under."""
    settings = shared.settings
    return StagedAggregator(
        config=shared.state.round_params.mask_config,
        object_size=shared.state.round_params.model_length,
        device=settings.aggregation.device,
        batch_size=settings.aggregation.batch_size,
        kernel=settings.aggregation.kernel,
        dispatch_ahead=settings.aggregation.dispatch_ahead,
        staging_buffers=settings.aggregation.staging_buffers,
        shard_parallel=settings.aggregation.shard_parallel,
        shard_threads=settings.aggregation.shard_threads,
        packed_staging=settings.aggregation.packed_staging,
        tenant=shared.tenant,
    )


class DeviceAggregation(Aggregation):
    """Aggregation view over the still-sharded device accumulator.

    ``finalize()`` materializes a host ``Aggregation`` — it GATHERS the
    whole mesh accumulator into one wire-layout host array before the
    Unmask phase has even subtracted the mask. This view keeps the
    accumulator where it is: ``unmask_array``/``unmask`` subtract the
    elected mask per-shard in place (``ShardedAggregator.unmask_limbs`` —
    each mesh device subtracts its own model-axis slice; the host
    ``mod_sub`` runs only when a native fold left the accumulator
    host-resident), and only the *unmasked* result crosses to the host for
    the fixed-point decode. Validation and the tiny unit channel need no
    accumulator read at all; ``object`` stays available for
    checkpoint/test paths that genuinely want the gathered aggregate.
    """

    def __init__(self, config: MaskConfigPair, object_size: int, device, unit_acc, stream=None):
        # deliberately NOT calling super().__init__: it would allocate an
        # empty host MaskObject of the full model size just to carry configs
        self._nb_models = device.nb_models
        self.object_size = object_size
        self._config = config
        self._device = device
        self._unit_acc = np.asarray(unit_acc)
        # deferred-drain handoff (docs/DESIGN.md §22): when the streaming
        # pipeline rides into Unmask still open, the eager per-shard
        # unmask subtracts each shard the moment ITS last fold commits
        self._stream = stream

    @property
    def nb_models(self) -> int:
        if self._stream is not None:
            # deferred drain: folds may still be in flight — read the
            # count atomically with the worker handoff, exactly as the
            # update phase's capacity checks did (it is exact once the
            # eager unmask's drain has settled the pipeline)
            return self._stream.counted_models()
        return self._nb_models

    @property
    def config(self) -> MaskConfigPair:
        return self._config

    @property
    def object(self) -> MaskObject:
        """Gathered host aggregate (checkpoints/tests only — the unmask
        path never calls this)."""
        if self._stream is not None:
            self._stream.drain()
        return MaskObject(
            MaskVect(self._config.vect, self._device.snapshot()),
            MaskUnit(self._config.unit, self._unit_acc),
        )

    def validate_unmasking(self, mask: MaskObject) -> None:
        if self.nb_models == 0:
            raise UnmaskingError("NoModel")
        if self.nb_models > self._config.vect.max_nb_models:
            raise UnmaskingError("TooManyModels")
        if self.nb_models > self._config.unit.max_nb_models:
            raise UnmaskingError("TooManyScalars")
        if self._config.vect != mask.vect.config or self.object_size != len(mask.vect):
            raise UnmaskingError("MaskManyMismatch")
        if self._config.unit != mask.unit.config:
            raise UnmaskingError("MaskOneMismatch")
        if not mask.is_valid():
            raise UnmaskingError("InvalidMask")

    def _settle_stream(self) -> None:
        """Close a deferred-drain pipeline and pin the final model count
        (everything has settled by now: drain ran, close re-drains)."""
        stream, self._stream = self._stream, None
        if stream is not None:
            stream.close()
            self._nb_models = self._device.nb_models

    def _eager_unmask(self, mask_obj: MaskObject) -> np.ndarray | None:
        """Eager per-shard unmask (docs/DESIGN.md §22): the mask subtract
        is staged as per-shard tail jobs BEHIND the round's last fold
        batches, so each shard unmasks the moment its own last fold
        commits — instead of global drain barrier, then a separate unmask
        pass. Returns ``None`` when the pipeline couldn't run it (caller
        falls back to the drain-time subtract, byte-identical either way:
        a failed shard's accumulator is untouched)."""
        stream = self._stream
        planar = self._device.mask_planar(mask_obj.vect.data)
        job = stream.stage_unmask(planar)
        try:
            # the deferred acceptance sync + completion barrier; fold
            # errors surface here exactly as they would have at the
            # sum2 finalize in the serial flow
            stream.drain()
        except Exception:
            self._settle_stream()
            raise
        out = stream.finish_unmask(job) if job is not None else None
        self._settle_stream()
        return out

    def _unmasked_limbs(self, mask_obj: MaskObject) -> tuple[np.ndarray, int]:
        # per-shard in-place subtract: the mask planes upload with the
        # accumulator's sharding and each device subtracts its own slice;
        # the gather happens AFTER the subtraction, on the unmasked result
        n_vect = self._eager_unmask(mask_obj) if self._stream is not None else None
        if n_vect is None:
            n_vect = self._device.unmask_limbs(mask_obj.vect.data)
        ol_u = limb_ops.order_limbs_for(self._config.unit.order)
        n_unit = limb_ops.mod_sub(
            self._unit_acc[None, :], np.asarray(mask_obj.unit.data)[None, :], ol_u
        )[0]
        return n_vect, limb_ops.limbs_to_int(n_unit)

    # the base implementations read configs through ``self.object`` —
    # which HERE would gather the mesh accumulator; re-expressed on the
    # carried config pair so unmasking never touches the property
    def unmask_array(self, mask_obj: MaskObject) -> np.ndarray:
        from ..core.mask.encode import (
            decode_scalar_sum,
            decode_vect_any,
            decode_vect_fast,
            has_fast_path,
        )

        n_vect, n_unit = self._unmasked_limbs(mask_obj)
        scalar_sum = decode_scalar_sum(n_unit, self._config.unit, self.nb_models)
        if has_fast_path(self._config.vect):
            return decode_vect_fast(n_vect, self._config.vect, self.nb_models, scalar_sum)
        return decode_vect_any(n_vect, self._config.vect, self.nb_models, scalar_sum)

    def unmask(self, mask_obj: MaskObject):
        from ..core.mask.encode import decode_scalar_sum, decode_vect_exact
        from ..core.mask.model import Model

        n_vect, n_unit = self._unmasked_limbs(mask_obj)
        scalar_sum = decode_scalar_sum(n_unit, self._config.unit, self.nb_models)
        values = limb_ops.limbs_to_ints(n_vect)
        return Model(decode_vect_exact(values, self._config.vect, self.nb_models, scalar_sum))

    def release_pool(self) -> None:
        """Round-end page release (the Unmask phase calls this AFTER the
        unmasked model is decoded and persisted — see
        ``StagedAggregator.release_pool``)."""
        self._device.release_plan_pages()


class StagedAggregator:
    """Stages validated masked updates and folds them in batches."""

    def __init__(
        self,
        config: MaskConfigPair,
        object_size: int,
        device: bool = False,
        batch_size: int = 64,
        ingest_workers: int = 4,
        mesh=None,
        kernel: str = "auto",
        dispatch_ahead: int = 2,
        staging_buffers: int = 3,
        shard_parallel: bool = True,
        shard_threads: int = 0,
        packed_staging: bool = True,
        tenant: str = "default",
    ):
        self.config = config
        self.object_size = object_size
        self.tenant = tenant
        self.batch_size = max(1, batch_size)
        self._staged_vect: list = []  # device: futures of planar arrays
        self._staged_unit: list[np.ndarray] = []
        self._count = 0
        self._host = Aggregation(config, object_size)
        self._device = None
        self._stream = None
        self._ingest_pool = None
        if device:
            from concurrent.futures import ThreadPoolExecutor

            from ..ops import limbs as limb_ops
            from ..parallel.aggregator import ShardedAggregator
            from ..parallel.streaming import StreamingAggregator

            self._device = ShardedAggregator(config.vect, object_size, mesh=mesh, kernel=kernel)
            # flush() submits micro-batches here; drain()/finalize() sync.
            # On a multi-device mesh the pipeline runs shard-parallel (one
            # fold worker per device, per-shard staging rings + donated
            # accumulators) unless [aggregation] shard_parallel = false
            self._stream = StreamingAggregator(
                self._device,
                staging_buffers=staging_buffers,
                dispatch_ahead=dispatch_ahead,
                max_batch=self.batch_size,
                shard_parallel=shard_parallel,
                shard_threads=shard_threads,
                packed=packed_staging,
                tenant=tenant,
            )
            # tiny unit part stays on host
            self._unit_acc = np.zeros(
                limb_ops.n_limbs_for_order(config.unit.order), dtype=np.uint32
            )
            # wire->planar transposes overlap across workers: at 25M params
            # each update is a ~200MB relayout, which would serialize the
            # ingest path if done at flush time on one thread
            self._ingest_pool = ThreadPoolExecutor(
                max_workers=max(1, ingest_workers), thread_name_prefix="xn-ingest"
            )

    @property
    def kernel_used(self) -> str:
        """Which fold kernel actually ran (``host`` off-device; on device the
        resolved choice, or the configured one before the first fold)."""
        if self._device is None:
            return "host"
        return self._device.kernel_used or self._device.kernel

    @property
    def nb_models(self) -> int:
        if self._device is not None:
            # staged + (in-flight + folded, read atomically with the fold
            # worker's handoff): every accepted update counts the moment it
            # is staged, exactly as before streaming
            return self._count + self._stream.counted_models()
        return self._count + self._host.nb_models

    def validate_aggregation(self, obj: MaskObject) -> None:
        """Per-update protocol validation (same checks as the reference,
        masking.rs:253-279) without materializing a probe accumulator."""
        if self.config.vect != obj.vect.config:
            raise AggregationError("ModelMismatch")
        if self.config.unit != obj.unit.config:
            raise AggregationError("ScalarMismatch")
        if self.object_size != len(obj.vect):
            raise AggregationError("ModelMismatch")
        if self.nb_models >= self.config.vect.max_nb_models:
            raise AggregationError("TooManyModels")
        if self.nb_models >= self.config.unit.max_nb_models:
            raise AggregationError("TooManyScalars")
        vect = obj.vect
        if (
            self._device is not None
            and isinstance(vect, LazyWireMaskVect)
            and not vect.materialized
        ):
            # device wire ingest: unpack + element validity run on the
            # accelerator, and the resulting planar is cached on the object
            # so stage() never re-uploads. Ordering is preserved — this runs
            # before the caller's seed-dict insert (update.rs:119-152). A
            # prior prevalidate_wire_batch may already have cached the
            # verdict (one device round-trip for the whole micro-batch);
            # only un-prevalidated updates pay the per-update sync here.
            planar = vect._staged_planar
            if planar is None and not vect._wire_invalid:
                if vect.planar:
                    # wire v2: the body is already the packed byte-planar
                    # layout — uploaded as-is, no byte gather either side
                    planar = self._device.validate_planar_update(vect.planar_block)
                else:
                    planar = self._device.validate_wire_update(np.asarray(vect.wire_block))
            if planar is None or not obj.unit.is_valid():
                raise AggregationError("InvalidObject")
            vect._staged_planar = planar
        elif not obj.is_valid():
            raise AggregationError("InvalidObject")

    def prevalidate_wire_batch(self, objs) -> None:
        """Batch device validation for a micro-batch about to be processed
        member-wise: ONE staged upload + unpack dispatch + acceptance fetch
        for the whole group (``ShardedAggregator.validate_wire_updates``),
        where the per-member path pays a full device round-trip sync each.
        Results are cached on the vect objects; ``validate_aggregation``
        consumes them per member in order, so the protocol's
        validate-before-seed-dict-insert sequencing is unchanged (caching a
        verdict earlier has no observable side effect). Non-wire members
        and host mode are untouched."""
        if self._device is None:
            return
        # only members the device branch would actually validate: matching
        # config and declared length (a count/config-mismatched member must
        # fall through to the per-member path, which rejects IT alone with
        # ModelMismatch — a ragged np.stack here would instead blow up the
        # whole micro-batch with an internal error)
        want_bytes = self.object_size * self.config.vect.bytes_per_number
        lazies = [
            obj.vect
            for obj in objs
            if isinstance(obj.vect, LazyWireMaskVect)
            and not obj.vect.materialized
            and obj.vect._staged_planar is None
            and not obj.vect._wire_invalid
            and obj.vect.config == self.config.vect
            and np.asarray(obj.vect.wire_block).size == want_bytes
        ]
        # v1 (interleaved) and v2 (planar) members batch separately — the
        # two unpack programs take different layouts — but a mixed group
        # still validates in at most two device round-trips
        for planar_wire in (False, True):
            group = [v for v in lazies if v.planar is planar_wire]
            for start in range(0, len(group), self.batch_size):
                chunk = group[start : start + self.batch_size]
                if planar_wire:
                    planars = self._device.validate_planar_updates(
                        [v.planar_block for v in chunk]
                    )
                else:
                    planars = self._device.validate_wire_updates(
                        [np.asarray(v.wire_block) for v in chunk]
                    )
                for vect, planar in zip(chunk, planars):
                    if planar is None:
                        vect._wire_invalid = True
                    else:
                        vect._staged_planar = planar

    def validate_partial(self, obj: MaskObject, members: int) -> None:
        """Protocol validation for an edge PARTIAL aggregate of ``members``
        updates: same config/length checks as a single update, but the
        model-count headroom must fit the whole member count (the envelope
        is atomic — it folds entirely or not at all)."""
        if members < 1:
            raise AggregationError("EmptyPartial")
        if self.config.vect != obj.vect.config:
            raise AggregationError("ModelMismatch")
        if self.config.unit != obj.unit.config:
            raise AggregationError("ScalarMismatch")
        if self.object_size != len(obj.vect):
            raise AggregationError("ModelMismatch")
        if self.nb_models + members > self.config.vect.max_nb_models:
            raise AggregationError("TooManyModels")
        if self.nb_models + members > self.config.unit.max_nb_models:
            raise AggregationError("TooManyScalars")
        if not obj.is_valid():
            raise AggregationError("InvalidObject")

    def fold_partial(self, obj: MaskObject, members: int) -> None:
        """Fold a pre-aggregated partial of ``members`` updates as ONE
        ``masked_add`` dispatch and advance ``nb_models`` by ``members``.

        Ordering: any singly-staged updates flush first, so the aggregate
        stays the plain modular sum of everything accepted so far (order
        never changes the result — this just keeps the accounting simple).
        """
        if members < 1:
            raise AggregationError("EmptyPartial")
        if self._device is not None:
            # drain() is the device sync point: with nothing in flight the
            # model-count adjustment below cannot race the fold worker
            self.drain()
            from ..ops import limbs as limb_ops
            from ..ops.fold_jax import wire_to_planar

            planar = wire_to_planar(np.asarray(obj.vect.data))
            padded = self._device.padded_length
            if planar.shape[1] != padded:
                planar = np.pad(planar, ((0, 0), (0, padded - planar.shape[1])))
            self._stream.submit_host_planar_rows([planar])
            self._stream.drain()
            # the partial counts as `members` models, not the one row folded
            self._device.nb_models += members - 1
            order_limbs = limb_ops.order_limbs_for(self.config.unit.order)
            self._unit_acc = limb_ops.mod_add(
                self._unit_acc[None, :], np.asarray(obj.unit.data)[None, :], order_limbs
            )[0]
        else:
            self.flush()
            profiling.timed_kernel(
                "masked_add",
                self.object_size,
                lambda: self._host.aggregate_partial(obj, members),
            )

    @property
    def pending(self) -> int:
        """Updates staged but not yet folded."""
        return self._count

    def stage(self, obj: MaskObject) -> None:
        """Stage an update without folding (caller controls flush timing)."""
        if self._ingest_pool is not None:
            planar_dev = (
                obj.vect._staged_planar if isinstance(obj.vect, LazyWireMaskVect) else None
            )
            if planar_dev is not None:
                # wire ingest: validate_aggregation already unpacked this
                # update on device — stage the device-resident planar
                self._staged_vect.append(planar_dev)
            else:
                from ..ops.fold_jax import wire_to_planar

                padded = self._device.padded_length

                def to_planar(data=obj.vect.data):
                    planar = wire_to_planar(data)
                    if planar.shape[1] != padded:
                        planar = np.pad(planar, ((0, 0), (0, padded - planar.shape[1])))
                    return planar

                self._staged_vect.append(self._ingest_pool.submit(to_planar))
        else:
            self._staged_vect.append(obj.vect.data)
        self._staged_unit.append(obj.unit.data)
        self._count += 1

    def aggregate(self, obj: MaskObject) -> None:
        self.stage(obj)
        if self._count >= self.batch_size:
            self.flush()

    def flush(self) -> None:
        """Hand the staged micro-batch to the fold backend.

        Device mode SUBMITS into the streaming pipeline and returns without
        waiting for the fold (the pipeline's dispatch-ahead/ring bounds
        provide backpressure); call :meth:`drain` to synchronize. Host mode
        folds inline as before.
        """
        if self._count == 0:
            return
        stack = None if self._ingest_pool is not None else np.stack(self._staged_vect)
        units = np.stack(self._staged_unit)
        if self._device is not None:
            import jax

            from ..ops import limbs as limb_ops

            parts = [p.result() if hasattr(p, "result") else p for p in self._staged_vect]
            self._staged_vect.clear()  # consume destructively: free as we fold
            # wire-v2 members stay PACKED uint8[bpn, padded] through staging
            # (bpn bytes/element vs the 4L a uint32 planar pins) and fold
            # through the fused packed kernel; a mixed round therefore
            # splits one flush by staged layout
            packed_rows = [
                p for p in parts if isinstance(p, jax.Array) and p.dtype == "uint8"
            ]
            parts = [
                p for p in parts if not (isinstance(p, jax.Array) and p.dtype == "uint8")
            ]
            if packed_rows:
                self._stream.fold_packed_rows_now(packed_rows)
                packed_rows.clear()
            if not parts:
                pass
            elif all(isinstance(p, jax.Array) for p in parts):
                # wire ingest: every planar is already device-resident and
                # validity-checked — folded INLINE (not queued: parking
                # device-resident batches behind dispatch_ahead would pin
                # several full batches in HBM at once, ~13 GB each at
                # 25M/batch 64, where XLA's async dispatch already overlaps
                # device folds). Chunked stack+fold keeps peak HBM at the
                # staged planars + one chunk-sized copy, the pre-streaming
                # bound.
                self._stream.fold_planar_rows_now(parts)
            else:
                # host planars: copied into the pipeline's staging ring
                # (no np.stack allocation) and folded by the worker while
                # this thread returns to staging the next micro-batch
                host_rows = [np.asarray(p) for p in parts]
                for start in range(0, len(host_rows), self._stream.max_batch):
                    self._stream.submit_host_planar_rows(
                        host_rows[start : start + self._stream.max_batch]
                    )
            parts.clear()
            order_limbs = limb_ops.order_limbs_for(self.config.unit.order)
            batch_unit = limb_ops.batch_mod_sum(units[:, None, :], order_limbs)[0]
            self._unit_acc = limb_ops.mod_add(
                self._unit_acc[None, :], batch_unit[None, :], order_limbs
            )[0]
        else:
            # same op label as the device fold: one /metrics series answers
            # "how fast is the masked add", whichever backend ran it
            profiling.timed_kernel(
                "masked_add",
                stack.shape[0] * self.object_size,
                lambda: self._host.aggregate_batch(stack, units),
            )
        self._staged_vect.clear()
        self._staged_unit.clear()
        self._count = 0

    def drain(self) -> None:
        """Flush, then block until every in-flight fold has completed (the
        phase-transition synchronization point)."""
        self.flush()
        if self._stream is not None:
            self._stream.drain()

    def snapshot_state(self) -> tuple[np.ndarray, np.ndarray, int]:
        """Exact host copy of the aggregate for a mid-round checkpoint.

        Drains first — the streaming pipeline's in-flight folds must land
        before the accumulator is read — then returns ``(vect wire
        uint32[model_len, L], unit uint32[L_unit], nb_models)``.
        """
        self.drain()
        if self._device is not None:
            return self._device.snapshot(), np.array(self._unit_acc), self._device.nb_models
        return (
            np.array(self._host.object.vect.data),
            np.array(self._host.object.unit.data),
            self._host.nb_models,
        )

    def snapshot_journal(self) -> AggSnapshot:
        """Exact host copy of the aggregate for a journal entry.

        Drains first, like :meth:`snapshot_state` — then, on the device
        path, reads the accumulator shard by shard (packed per-shard
        planar planes) instead of reassembling the mesh array into one
        global wire buffer: each shard's plane crosses to the host once,
        and no device-side concat/relayout runs at all.
        """
        self.drain()
        if self._device is not None:
            planes = self._device.snapshot_shards()
            if planes is not None:
                return AggSnapshot(
                    nb_models=self._device.nb_models,
                    unit=np.array(self._unit_acc),
                    planes=planes,
                )
            return AggSnapshot(
                nb_models=self._device.nb_models,
                unit=np.array(self._unit_acc),
                vect=self._device.snapshot(),
            )
        return AggSnapshot(
            nb_models=self._host.nb_models,
            unit=np.array(self._host.object.unit.data),
            vect=np.array(self._host.object.vect.data),
        )

    def restore_journal(self, ckpt) -> None:
        """Restore a journal entry (``RoundCheckpoint``) into an EMPTY
        aggregator. Per-shard planes restore shard-by-shard on the device
        path (``ShardedAggregator.restore_shards`` — no host concat when
        the plane geometry matches the mesh); everything else goes through
        the wire-layout :meth:`restore_state`. An empty entry (``nb_models
        == 0``: the sealed-sum-dict entry written at the Sum→Update
        transition) restores to the zero accumulator the constructor
        already built."""
        if ckpt.nb_models == 0:
            return
        if self._device is not None and ckpt.planes:
            if self._count or self.nb_models:
                raise RuntimeError("restore_journal requires an empty aggregator")
            self._device.restore_shards(ckpt.planes, ckpt.nb_models)
            self._unit_acc = np.ascontiguousarray(ckpt.unit, dtype=np.uint32)
            return
        self.restore_state(ckpt.wire_vect(), ckpt.unit, ckpt.nb_models)

    def restore_state(self, vect: np.ndarray, unit: np.ndarray, nb_models: int) -> None:
        """Restore a checkpoint snapshot into an EMPTY aggregator (resume)."""
        if self._count or self.nb_models:
            raise RuntimeError("restore_state requires an empty aggregator")
        vect = np.ascontiguousarray(vect, dtype=np.uint32)
        unit = np.ascontiguousarray(unit, dtype=np.uint32)
        if self._device is not None:
            self._device.restore(vect, nb_models)
            self._unit_acc = unit
        else:
            self._host.object = MaskObject(
                MaskVect(self.config.vect, vect), MaskUnit(self.config.unit, unit)
            )
            self._host.nb_models = nb_models

    def finalize(self) -> Aggregation:
        """Materialize the protocol-level ``Aggregation`` (for Unmask)."""
        self.drain()
        if self._device is None:
            return self._host
        self._stream.close()
        agg = Aggregation(self.config, self.object_size)
        agg.object = MaskObject(
            MaskVect(self.config.vect, self._device.snapshot()),
            MaskUnit(self.config.unit, self._unit_acc),
        )
        agg.nb_models = self._device.nb_models
        return agg

    def release_pool(self) -> None:
        """Round-end page release (the Unmask tail, docs/DESIGN.md §19):
        the shard plan's leased accumulator pages go back to the shared
        pool once the unmasked model is decoded — nothing reads the
        accumulator past this point, so the pool may re-lease the pages to
        another tenant immediately."""
        if self._device is not None:
            self._device.release_plan_pages()

    def finalize_inplace(self, defer_drain: bool = False) -> Aggregation:
        """The Unmask handoff WITHOUT gathering the accumulator.

        Host mode is unchanged (the accumulator is host-resident — its
        ``mod_sub`` is the right unmask). Device mode returns a
        :class:`DeviceAggregation` view over the still-sharded accumulator,
        so the Unmask phase subtracts the elected mask per-shard in place
        and only the unmasked result crosses to the host for decode —
        ``finalize()`` (kept for snapshot/test callers) gathers first and
        subtracts after, a full extra accumulator round-trip at 25M params.

        With ``defer_drain`` (``[overlap] eager_unmask``, docs/DESIGN.md
        §22) the device pipeline rides into Unmask still OPEN: the staged
        remainder is submitted but the drain barrier moves into the eager
        unmask, where each shard subtracts its mask slice the moment its
        own last fold commits instead of after a global drain plus a
        separate unmask pass.
        """
        if defer_drain and self._device is not None:
            self.flush()
            return DeviceAggregation(
                self.config, self.object_size, self._device, self._unit_acc,
                stream=self._stream,
            )
        self.drain()
        if self._device is None:
            return self._host
        self._stream.close()
        return DeviceAggregation(
            self.config, self.object_size, self._device, self._unit_acc
        )
