"""Coordinator process wiring and entry point.

Functional port of the reference's startup (reference:
rust/xaynet-server/src/bin/main.rs:29-138): settings -> logging -> metrics ->
store -> state-machine initializer -> REST server, with the state machine
and the API as the two long-lived tasks.

Run:  python -m xaynet_tpu.server.runner -c configs/config.toml
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
from typing import Optional

from ..storage.memory import (
    FileCoordinatorStorage,
    FilesystemModelStorage,
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from ..storage.traits import Store
from ..telemetry import BridgedMetrics, RoundReporter
from ..utils import tracing
from .metrics import InfluxHttpMetrics, InfluxLineMetrics, JsonlMetrics, LogMetrics
from .rest import RestServer
from .services import Fetcher, PetMessageHandler
from .settings import Settings
from .state_machine import StateMachineInitializer

logger = logging.getLogger("xaynet.coordinator")


def init_store(settings: Settings, tenant: str = "default") -> Store:
    # tenant-scoped storage keys (docs/DESIGN.md §19): a non-default tenant
    # prefixes every durable key — redis keys get "t:<tenant>:", file/
    # filesystem backends get a "t-<tenant>" subtree — so N tenants share
    # one backend without key collisions. The default tenant keeps the
    # historical flat layout (single-tenant deployments are unchanged).
    scoped_dir = settings.storage.model_dir
    if tenant != "default":
        import os as _os

        scoped_dir = _os.path.join(settings.storage.model_dir, f"t-{tenant}")
    if settings.storage.coordinator == "redis":
        from ..storage.redis import RedisCoordinatorStorage

        coordinator = RedisCoordinatorStorage(
            host=settings.storage.redis_host,
            port=settings.storage.redis_port,
            db=settings.storage.redis_db,
            key_prefix="" if tenant == "default" else f"t:{tenant}:",
        )
    elif settings.storage.coordinator == "file":
        import os

        os.makedirs(scoped_dir, exist_ok=True)
        coordinator = FileCoordinatorStorage(
            os.path.join(scoped_dir, "coordinator_state.json")
        )
    else:
        coordinator = InMemoryCoordinatorStorage()
    if settings.storage.backend == "filesystem":
        models = FilesystemModelStorage(scoped_dir)
    elif settings.storage.backend == "s3":
        from ..storage.s3 import S3ModelStorage

        models = S3ModelStorage(
            endpoint=settings.storage.s3_endpoint,
            bucket=settings.storage.s3_bucket,
            access_key=settings.storage.s3_access_key,
            secret_key=settings.storage.s3_secret_key,
            region=settings.storage.s3_region,
        )
    else:
        # memory archives EVERY round's model in RAM (a slow leak in a
        # long-running coordinator) — fine for tests/benches, wrong for
        # production; configs/config.toml documents filesystem as default
        logging.getLogger("xaynet.runner").warning(
            "model storage backend 'memory' keeps all round models in RAM; "
            "use [storage] backend = \"filesystem\" in production"
        )
        models = InMemoryModelStorage()
    return Store(coordinator, models, NoOpTrustAnchor())


def init_metrics(settings: Settings):
    if not settings.metrics.enable:
        return None
    if settings.metrics.sink == "jsonl":
        return JsonlMetrics(settings.metrics.path)
    if settings.metrics.sink == "influx":
        return InfluxLineMetrics(settings.metrics.path)
    if settings.metrics.sink == "influx-http":
        return InfluxHttpMetrics(settings.metrics.url, settings.metrics.database)
    return LogMetrics()


def init_logging(settings: Settings) -> None:
    """Default logging with request-id correlation: every record carries
    ``%(request_id)s`` (set by ``tracing.RequestIdFilter`` from the
    contextvar the message pipeline assigns), so one grep on an id yields
    the full path of a message through pipeline and state machine."""
    logging.basicConfig(
        level=getattr(logging, settings.log.filter.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s [%(request_id)s] %(message)s",
    )
    # the filter must sit on the handlers: logger-level filters don't apply
    # to records propagated from child loggers
    for handler in logging.getLogger().handlers:
        if not any(isinstance(f, tracing.RequestIdFilter) for f in handler.filters):
            handler.addFilter(tracing.RequestIdFilter())


async def serve(settings: Settings, store: Optional[Store] = None) -> None:
    if settings.tenancy.enabled:
        # multi-tenant wiring: one process, one REST listener, N tenant
        # round pipelines over the shared mesh/pool/scheduler (§19)
        await serve_tenants(settings)
        return
    import time as _time

    boot_t0 = _time.monotonic()
    init_logging(settings)
    store = store if store is not None else init_store(settings)
    if settings.storage.backend == "s3":
        # reference creates the bucket at startup (main.rs init_store path)
        from ..storage.s3 import S3ModelStorage

        if isinstance(store.models, S3ModelStorage):
            await store.models.create_bucket()
    # deterministic chaos: a configured fault plan installs process-wide
    # BEFORE the resilient wrapper, so storage/ingest/streaming sites all
    # see the same seeded schedule (tools/soak.py --faults drives this)
    if settings.resilience.fault_plan:
        from ..resilience import FaultPlan, install_plan

        install_plan(FaultPlan.parse(settings.resilience.fault_plan))
        logger.warning("fault plan installed: %s", settings.resilience.fault_plan)
    # every storage call flows through retry + circuit breaker from here on
    from ..resilience import wrap_store

    store = wrap_store(store, settings.resilience)
    # registry-first telemetry: the configured sink (if any) and the
    # per-round JSON reporter both consume the bridge's measurements
    reporter = (
        RoundReporter(settings.metrics.round_report_path)
        if settings.metrics.round_report_path
        else None
    )
    metrics = BridgedMetrics(sink=init_metrics(settings), reporter=reporter)
    # distributed round tracing + flight recorder (docs/DESIGN.md §16):
    # [metrics] trace/trace_dir/flight_dir override the env defaults
    from ..telemetry import recorder as flight_recorder, tracing as trace

    trace.get_tracer().configure(
        # empty settings defer to the env defaults the Tracer already read
        # (XAYNET_TRACE / XAYNET_TRACE_DIR); explicit config wins
        mode=settings.metrics.trace or None,
        trace_dir=settings.metrics.trace_dir or None,
    )
    flight_recorder.get_recorder().configure(settings.metrics.flight_dir or None)
    # per-tenant SLO targets + burn-rate alerting over the always-on
    # round-wall timeline (docs/DESIGN.md §20)
    from ..telemetry import slo as slo_engine

    slo_engine.configure(settings.slo)
    # warm kernel-calibration verdicts (docs/DESIGN.md §22): with
    # XAYNET_CALIB_CACHE set, the fold/mask probe races a previous process
    # ran load here instead of inside the first round's wall
    from ..utils import calibcache

    calibcache.configure_from_env()
    initializer = StateMachineInitializer(settings, store, metrics)
    machine, request_tx, events = await initializer.init()

    handler = PetMessageHandler(
        events, request_tx, wire_ingest=settings.aggregation.wire_ingest
    )
    fetcher = Fetcher(events)
    pipeline = None
    if settings.ingest.enabled:
        from ..ingest import IngestPipeline

        pipeline = IngestPipeline(handler, request_tx, events, settings.ingest)
        await pipeline.start()
    edge_api = None
    if settings.edge.enabled:
        from ..edge.api import EdgeCoordinatorApi

        edge_api = EdgeCoordinatorApi(events, request_tx, token=settings.edge.token)
        logger.info("edge tier enabled: serving /edge/round + /edge/envelope")
    rest = RestServer(
        fetcher, handler, registry=metrics.registry, pipeline=pipeline, edge_api=edge_api
    )
    host, _, port = settings.api.bind_address.partition(":")
    tls = None
    if settings.api.tls_certificate:
        import ssl

        tls = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        tls.load_cert_chain(settings.api.tls_certificate, settings.api.tls_key)
        if settings.api.tls_client_auth:
            tls.verify_mode = ssl.CERT_REQUIRED
            tls.load_verify_locations(settings.api.tls_client_auth)
    await rest.start(host or "127.0.0.1", int(port or 8081), tls)
    # restart-to-serving wall (docs/DESIGN.md §9): process entry to the API
    # accepting requests, store restore + journal resume included — THE
    # recovery-time number the kill-matrix bench gate tracks
    from ..resilience.checkpoint import RECOVERY_SECONDS

    RECOVERY_SECONDS.set(_time.monotonic() - boot_t0)

    stop = asyncio.get_running_loop().create_future()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            asyncio.get_running_loop().add_signal_handler(sig, lambda: stop.cancel())
        except NotImplementedError:  # pragma: no cover (non-unix)
            pass

    machine_task = asyncio.create_task(machine.run())
    try:
        done, _ = await asyncio.wait(
            [machine_task, stop], return_when=asyncio.FIRST_COMPLETED
        )
    except asyncio.CancelledError:
        pass
    finally:
        # graceful-signal flush (docs/DESIGN.md §9): capture the running
        # phase's journal hook BEFORE cancelling — a SIGTERM between the
        # update phase's save cadence points must not drop accepted updates
        phase = machine.phase
        flush = getattr(phase.shared, "flush_hook", None) if phase is not None else None
        machine_task.cancel()
        await asyncio.gather(machine_task, return_exceptions=True)
        if flush is not None:
            try:
                await flush()
                logger.info("graceful shutdown: final journal entry flushed")
            except Exception as err:
                logger.warning("graceful shutdown: journal flush failed: %s", err)
        # a cancelled machine never reaches the Shutdown phase, so close the
        # request channel here: queued/in-flight requests are rejected and
        # the pipeline's final coalescer flush fails fast instead of
        # awaiting a state machine that will never answer
        request_tx.close()
        await rest.stop()
        if pipeline is not None:
            await pipeline.stop()
        # flush the in-flight round report and drain the dispatcher thread's
        # queued tail — without this the InfluxHttp dispatcher dies with
        # whatever was still batching
        metrics.close()
        # forensic tail: the flight ring (recent spans + counter deltas)
        # lands on disk with every orderly exit, so a post-mortem has the
        # same bundle a crash dump would carry
        flight_recorder.flight_dump(
            "shutdown", "coordinator stopping (signal or machine exit)"
        )
        # ... and the in-flight round's trace window (Chrome export)
        trace.get_tracer().end_round()
        logger.info("coordinator stopped")


def _tenant_settings(base: Settings, tenant: str) -> Settings:
    """One tenant's effective settings: ``config_dir/<tenant>.toml`` when
    present (full settings file, normal loader + env overrides), else a
    copy of the base. The per-tenant copy never re-enters multi-tenant
    wiring (its [tenancy] section is cleared)."""
    import copy

    from .settings import TenancySettings

    cfg = None
    if base.tenancy.config_dir:
        path = os.path.join(base.tenancy.config_dir, f"{tenant}.toml")
        if os.path.exists(path):
            cfg = Settings.load(path)
            logger.info("tenant %s: settings loaded from %s", tenant, path)
    if cfg is None:
        cfg = copy.deepcopy(base)
    cfg.tenancy = TenancySettings()
    return cfg


async def _build_tenant_context(settings: Settings, tenant: str, budget, registry):
    """Build ONE tenant's full round pipeline and register it: scoped
    store, resilient wrapper, metrics bridge, phase machine, handler,
    fetcher, ingest pipeline and edge api. Shared by the serve_tenants
    boot loop and the lifecycle manager's runtime onboard — the runtime
    path builds tenants with exactly the wiring boot-time ones get.
    Returns ``(TenantContext, TenantRoutes)`` (the machine task is NOT
    started here; the caller owns task lifetime)."""
    from ..ingest import IngestPipeline
    from ..resilience import wrap_store
    from ..tenancy import TenantContext
    from .rest import TenantRoutes

    tset = _tenant_settings(settings, tenant)
    raw_store = init_store(tset, tenant)
    if tset.storage.backend == "s3":
        # same startup contract as the single-tenant serve() path:
        # the bucket must exist before the first model save
        from ..storage.s3 import S3ModelStorage

        if isinstance(raw_store.models, S3ModelStorage):
            await raw_store.models.create_bucket()
    store = wrap_store(raw_store, tset.resilience, tenant=tenant)
    reporter = (
        RoundReporter(tset.metrics.round_report_path, tenant=tenant)
        if tset.metrics.round_report_path
        else None
    )
    metrics = BridgedMetrics(sink=init_metrics(tset), reporter=reporter)
    initializer = StateMachineInitializer(tset, store, metrics, tenant=tenant)
    machine, request_tx, events = await initializer.init()
    handler = PetMessageHandler(
        events, request_tx, wire_ingest=tset.aggregation.wire_ingest
    )
    fetcher = Fetcher(events)
    pipeline = None
    if tset.ingest.enabled:
        pipeline = IngestPipeline(
            handler, request_tx, events, tset.ingest,
            tenant=tenant, budget=budget,
        )
        await pipeline.start()
    edge_api = None
    if tset.edge.enabled:
        from ..edge.api import EdgeCoordinatorApi

        edge_api = EdgeCoordinatorApi(events, request_tx, token=tset.edge.token)
    ctx = registry.add(
        TenantContext(
            tenant=tenant,
            settings=tset,
            store=store,
            machine=machine,
            request_tx=request_tx,
            events=events,
            handler=handler,
            fetcher=fetcher,
            pipeline=pipeline,
            edge_api=edge_api,
            metrics=metrics,
        )
    )
    troutes = TenantRoutes(
        fetcher=fetcher,
        handler=handler,
        pipeline=pipeline,
        edge_api=edge_api,
    )
    logger.info(
        "tenant %s: model_len=%d group=%s (round pipeline up)",
        tenant,
        tset.model.length,
        tset.mask.group_type.name,
    )
    return ctx, troutes


async def serve_tenants(settings: Settings) -> None:
    """Multi-tenant coordinator (docs/DESIGN.md §19, §23): one process
    serves every ``[tenancy] tenants`` id — each a full, independent round
    pipeline (scoped store, request channel, ingest, phase machine) —
    over ONE mesh, ONE paged accumulator pool, ONE fold-batch scheduler
    and ONE REST listener routing ``/t/<tenant>/...`` (the first tenant
    also serves the bare legacy routes). With ``[tenancy] admin_token``
    set, the tenant set is ELASTIC: ``/admin/tenants`` onboards, drains
    and reconfigures tenants at runtime through the lifecycle manager."""
    from ..telemetry import recorder as flight_recorder, tracing as trace
    from ..tenancy import (
        TenantAdmissionBudget,
        TenantLifecycle,
        TenantRegistry,
        configure_pool,
        configure_scheduler,
        install_manager,
    )
    from .rest import TenantRoutes

    import time as _time

    boot_t0 = _time.monotonic()
    init_logging(settings)
    ten = settings.tenancy
    configure_pool(ten.page_kib, ten.slab_pages, ten.host_pages, ten.device_pages)
    configure_scheduler(ten.max_inflight_folds)
    budget = TenantAdmissionBudget(ten.ingest_capacity, ten.max_share)
    if settings.resilience.fault_plan:
        from ..resilience import FaultPlan, install_plan

        install_plan(FaultPlan.parse(settings.resilience.fault_plan))
        logger.warning("fault plan installed: %s", settings.resilience.fault_plan)
    trace.get_tracer().configure(
        mode=settings.metrics.trace or None,
        trace_dir=settings.metrics.trace_dir or None,
    )
    flight_recorder.get_recorder().configure(settings.metrics.flight_dir or None)
    # the SLO engine is process-wide (per-tenant state inside): configured
    # once from the base settings' [slo] section, tenant targets included
    from ..telemetry import slo as slo_engine

    slo_engine.configure(settings.slo)
    from ..utils import calibcache

    calibcache.configure_from_env()

    registry = TenantRegistry()
    routes: dict[str, TenantRoutes] = {}
    for tenant in ten.tenants:
        _, troutes = await _build_tenant_context(settings, tenant, budget, registry)
        routes[tenant] = troutes

    # elastic lifecycle (docs/DESIGN.md §23): the manager owns runtime
    # onboard/drain over the SAME builder the boot loop used, fault
    # quarantine fed by the phase close paths, and the SLO->scheduler
    # demotion feedback loop
    lifecycle = TenantLifecycle(
        ten,
        registry,
        routes,
        budget=budget,
        builder=lambda t: _build_tenant_context(settings, t, budget, registry),
    )
    install_manager(lifecycle)
    lifecycle.install_slo_hook(slo_engine.get_engine())
    for tenant in registry.ids():
        lifecycle.mark_serving(tenant)

    default = registry.default
    rest = RestServer(
        default.fetcher,
        default.handler,
        registry=default.metrics.registry,
        pipeline=default.pipeline,
        edge_api=default.edge_api,
        tenants=routes,
        lifecycle=lifecycle,
        admin_token=ten.admin_token,
        default_tenant=default.tenant,
    )
    host, _, port = settings.api.bind_address.partition(":")
    tls = None
    if settings.api.tls_certificate:
        import ssl

        tls = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        tls.load_cert_chain(settings.api.tls_certificate, settings.api.tls_key)
        if settings.api.tls_client_auth:
            tls.verify_mode = ssl.CERT_REQUIRED
            tls.load_verify_locations(settings.api.tls_client_auth)
    await rest.start(host or "127.0.0.1", int(port or 8081), tls)
    # restart-to-serving wall: EVERY tenant's store restore + journal
    # resume ran before the listener came up (each tenant resumes
    # independently from its scoped journal)
    from ..resilience.checkpoint import RECOVERY_SECONDS

    RECOVERY_SECONDS.set(_time.monotonic() - boot_t0)
    logger.info(
        "multi-tenant coordinator up: %d tenants (%s), default=%s",
        len(registry),
        ", ".join(registry.ids()),
        default.tenant,
    )

    stop = asyncio.get_running_loop().create_future()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            asyncio.get_running_loop().add_signal_handler(sig, lambda: stop.cancel())
        except NotImplementedError:  # pragma: no cover (non-unix)
            pass

    for ctx in registry.contexts():
        ctx.task = asyncio.create_task(
            ctx.machine.run(), name=f"machine-{ctx.tenant}"
        )
    try:
        # the task set is DYNAMIC under the elastic lifecycle: drained
        # tenants' tasks get cancelled (that must not stop the process),
        # onboarded tenants add new ones — so re-derive the watch set from
        # the registry each pass and only exit when a task belonging to a
        # still-registered tenant finishes (a machine reaching Shutdown)
        # or the stop future fires
        while True:
            tasks = [c.task for c in registry.contexts() if c.task is not None]
            done, _ = await asyncio.wait(
                [*tasks, stop], return_when=asyncio.FIRST_COMPLETED
            )
            if stop in done:
                break
            live = {c.task for c in registry.contexts()}
            if any(t in live for t in done):
                break
    except asyncio.CancelledError:
        pass
    finally:
        from ..tenancy import install_manager as _uninstall

        _uninstall(None)
        # graceful-signal flush, per tenant: capture each running phase's
        # journal hook BEFORE cancelling its machine task
        flushes = []
        for ctx in registry.contexts():
            phase = ctx.machine.phase
            hook = getattr(phase.shared, "flush_hook", None) if phase is not None else None
            if hook is not None:
                flushes.append((ctx.tenant, hook))
        tasks = [c.task for c in registry.contexts() if c.task is not None]
        for ctx in registry.contexts():
            if ctx.task is not None:
                ctx.task.cancel()
            # same rationale as the single-tenant path: reject queued +
            # in-flight requests so draining components fail fast —
            # strictly per channel, one tenant's shutdown never strands
            # another tenant's requests
            ctx.request_tx.close()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        for tenant, hook in flushes:
            try:
                await hook()
                logger.info("tenant %s: final journal entry flushed", tenant)
            except Exception as err:
                logger.warning("tenant %s: journal flush failed: %s", tenant, err)
        await rest.stop()
        for ctx in registry.contexts():
            if ctx.pipeline is not None:
                await ctx.pipeline.stop()
            ctx.metrics.close()
        flight_recorder.flight_dump(
            "shutdown", "multi-tenant coordinator stopping"
        )
        trace.get_tracer().end_round()
        logger.info("multi-tenant coordinator stopped")


def _pin_jax_platform() -> None:
    """Make ``JAX_PLATFORMS`` authoritative for the coordinator process.

    Site configurations that register experimental accelerator plugins can
    override ``jax_platforms`` at import time; when ``aggregation.device`` is
    on, the first fold would then initialize that backend even though the
    operator asked for another (and a dead accelerator tunnel hangs backend
    init forever). Re-assert the env var on the live config before any
    backend is touched. No-op when the operator didn't set it.
    """
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def _enable_jax_compile_cache(settings: Settings) -> None:
    """Persist XLA/Mosaic compiles across coordinator restarts.

    A restarted coordinator (rolling deploy, crash recovery) should not pay
    the 20-40 s first-compile of the fold kernels again; the cache also
    lets short accelerator sessions reuse earlier builds. Only active when
    device aggregation is on — the host path never compiles.
    """
    if not settings.aggregation.device:
        return
    import jax

    cache_dir = os.environ.get("XAYNET_JAX_CACHE", "/tmp/xaynet_jax_cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # a bad cache dir must never stop the coordinator
        logger.warning("jax compile cache unavailable at %s: %s", cache_dir, e)


def main() -> None:
    parser = argparse.ArgumentParser(description="xaynet-tpu coordinator")
    parser.add_argument("-c", "--config", help="TOML configuration file", default=None)
    args = parser.parse_args()
    settings = Settings.load(args.config)
    _pin_jax_platform()
    _enable_jax_compile_cache(settings)
    asyncio.run(serve(settings))


if __name__ == "__main__":
    main()
