"""Coordinator process wiring and entry point.

Functional port of the reference's startup (reference:
rust/xaynet-server/src/bin/main.rs:29-138): settings -> logging -> metrics ->
store -> state-machine initializer -> REST server, with the state machine
and the API as the two long-lived tasks.

Run:  python -m xaynet_tpu.server.runner -c configs/config.toml
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
from typing import Optional

from ..storage.memory import (
    FileCoordinatorStorage,
    FilesystemModelStorage,
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from ..storage.traits import Store
from ..telemetry import BridgedMetrics, RoundReporter
from ..utils import tracing
from .metrics import InfluxHttpMetrics, InfluxLineMetrics, JsonlMetrics, LogMetrics
from .rest import RestServer
from .services import Fetcher, PetMessageHandler
from .settings import Settings
from .state_machine import StateMachineInitializer

logger = logging.getLogger("xaynet.coordinator")


def init_store(settings: Settings) -> Store:
    if settings.storage.coordinator == "redis":
        from ..storage.redis import RedisCoordinatorStorage

        coordinator = RedisCoordinatorStorage(
            host=settings.storage.redis_host,
            port=settings.storage.redis_port,
            db=settings.storage.redis_db,
        )
    elif settings.storage.coordinator == "file":
        import os

        coordinator = FileCoordinatorStorage(
            os.path.join(settings.storage.model_dir, "coordinator_state.json")
        )
    else:
        coordinator = InMemoryCoordinatorStorage()
    if settings.storage.backend == "filesystem":
        models = FilesystemModelStorage(settings.storage.model_dir)
    elif settings.storage.backend == "s3":
        from ..storage.s3 import S3ModelStorage

        models = S3ModelStorage(
            endpoint=settings.storage.s3_endpoint,
            bucket=settings.storage.s3_bucket,
            access_key=settings.storage.s3_access_key,
            secret_key=settings.storage.s3_secret_key,
            region=settings.storage.s3_region,
        )
    else:
        # memory archives EVERY round's model in RAM (a slow leak in a
        # long-running coordinator) — fine for tests/benches, wrong for
        # production; configs/config.toml documents filesystem as default
        logging.getLogger("xaynet.runner").warning(
            "model storage backend 'memory' keeps all round models in RAM; "
            "use [storage] backend = \"filesystem\" in production"
        )
        models = InMemoryModelStorage()
    return Store(coordinator, models, NoOpTrustAnchor())


def init_metrics(settings: Settings):
    if not settings.metrics.enable:
        return None
    if settings.metrics.sink == "jsonl":
        return JsonlMetrics(settings.metrics.path)
    if settings.metrics.sink == "influx":
        return InfluxLineMetrics(settings.metrics.path)
    if settings.metrics.sink == "influx-http":
        return InfluxHttpMetrics(settings.metrics.url, settings.metrics.database)
    return LogMetrics()


def init_logging(settings: Settings) -> None:
    """Default logging with request-id correlation: every record carries
    ``%(request_id)s`` (set by ``tracing.RequestIdFilter`` from the
    contextvar the message pipeline assigns), so one grep on an id yields
    the full path of a message through pipeline and state machine."""
    logging.basicConfig(
        level=getattr(logging, settings.log.filter.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s [%(request_id)s] %(message)s",
    )
    # the filter must sit on the handlers: logger-level filters don't apply
    # to records propagated from child loggers
    for handler in logging.getLogger().handlers:
        if not any(isinstance(f, tracing.RequestIdFilter) for f in handler.filters):
            handler.addFilter(tracing.RequestIdFilter())


async def serve(settings: Settings, store: Optional[Store] = None) -> None:
    init_logging(settings)
    store = store if store is not None else init_store(settings)
    if settings.storage.backend == "s3":
        # reference creates the bucket at startup (main.rs init_store path)
        from ..storage.s3 import S3ModelStorage

        if isinstance(store.models, S3ModelStorage):
            await store.models.create_bucket()
    # deterministic chaos: a configured fault plan installs process-wide
    # BEFORE the resilient wrapper, so storage/ingest/streaming sites all
    # see the same seeded schedule (tools/soak.py --faults drives this)
    if settings.resilience.fault_plan:
        from ..resilience import FaultPlan, install_plan

        install_plan(FaultPlan.parse(settings.resilience.fault_plan))
        logger.warning("fault plan installed: %s", settings.resilience.fault_plan)
    # every storage call flows through retry + circuit breaker from here on
    from ..resilience import wrap_store

    store = wrap_store(store, settings.resilience)
    # registry-first telemetry: the configured sink (if any) and the
    # per-round JSON reporter both consume the bridge's measurements
    reporter = (
        RoundReporter(settings.metrics.round_report_path)
        if settings.metrics.round_report_path
        else None
    )
    metrics = BridgedMetrics(sink=init_metrics(settings), reporter=reporter)
    # distributed round tracing + flight recorder (docs/DESIGN.md §16):
    # [metrics] trace/trace_dir/flight_dir override the env defaults
    from ..telemetry import recorder as flight_recorder, tracing as trace

    trace.get_tracer().configure(
        # empty settings defer to the env defaults the Tracer already read
        # (XAYNET_TRACE / XAYNET_TRACE_DIR); explicit config wins
        mode=settings.metrics.trace or None,
        trace_dir=settings.metrics.trace_dir or None,
    )
    flight_recorder.get_recorder().configure(settings.metrics.flight_dir or None)
    initializer = StateMachineInitializer(settings, store, metrics)
    machine, request_tx, events = await initializer.init()

    handler = PetMessageHandler(
        events, request_tx, wire_ingest=settings.aggregation.wire_ingest
    )
    fetcher = Fetcher(events)
    pipeline = None
    if settings.ingest.enabled:
        from ..ingest import IngestPipeline

        pipeline = IngestPipeline(handler, request_tx, events, settings.ingest)
        await pipeline.start()
    edge_api = None
    if settings.edge.enabled:
        from ..edge.api import EdgeCoordinatorApi

        edge_api = EdgeCoordinatorApi(events, request_tx, token=settings.edge.token)
        logger.info("edge tier enabled: serving /edge/round + /edge/envelope")
    rest = RestServer(
        fetcher, handler, registry=metrics.registry, pipeline=pipeline, edge_api=edge_api
    )
    host, _, port = settings.api.bind_address.partition(":")
    tls = None
    if settings.api.tls_certificate:
        import ssl

        tls = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        tls.load_cert_chain(settings.api.tls_certificate, settings.api.tls_key)
        if settings.api.tls_client_auth:
            tls.verify_mode = ssl.CERT_REQUIRED
            tls.load_verify_locations(settings.api.tls_client_auth)
    await rest.start(host or "127.0.0.1", int(port or 8081), tls)

    stop = asyncio.get_running_loop().create_future()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            asyncio.get_running_loop().add_signal_handler(sig, lambda: stop.cancel())
        except NotImplementedError:  # pragma: no cover (non-unix)
            pass

    machine_task = asyncio.create_task(machine.run())
    try:
        done, _ = await asyncio.wait(
            [machine_task, stop], return_when=asyncio.FIRST_COMPLETED
        )
    except asyncio.CancelledError:
        pass
    finally:
        machine_task.cancel()
        # a cancelled machine never reaches the Shutdown phase, so close the
        # request channel here: queued/in-flight requests are rejected and
        # the pipeline's final coalescer flush fails fast instead of
        # awaiting a state machine that will never answer
        request_tx.close()
        await rest.stop()
        if pipeline is not None:
            await pipeline.stop()
        # flush the in-flight round report and drain the dispatcher thread's
        # queued tail — without this the InfluxHttp dispatcher dies with
        # whatever was still batching
        metrics.close()
        # ... and the in-flight round's trace window (Chrome export)
        trace.get_tracer().end_round()
        logger.info("coordinator stopped")


def _pin_jax_platform() -> None:
    """Make ``JAX_PLATFORMS`` authoritative for the coordinator process.

    Site configurations that register experimental accelerator plugins can
    override ``jax_platforms`` at import time; when ``aggregation.device`` is
    on, the first fold would then initialize that backend even though the
    operator asked for another (and a dead accelerator tunnel hangs backend
    init forever). Re-assert the env var on the live config before any
    backend is touched. No-op when the operator didn't set it.
    """
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def _enable_jax_compile_cache(settings: Settings) -> None:
    """Persist XLA/Mosaic compiles across coordinator restarts.

    A restarted coordinator (rolling deploy, crash recovery) should not pay
    the 20-40 s first-compile of the fold kernels again; the cache also
    lets short accelerator sessions reuse earlier builds. Only active when
    device aggregation is on — the host path never compiles.
    """
    if not settings.aggregation.device:
        return
    import jax

    cache_dir = os.environ.get("XAYNET_JAX_CACHE", "/tmp/xaynet_jax_cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # a bad cache dir must never stop the coordinator
        logger.warning("jax compile cache unavailable at %s: %s", cache_dir, e)


def main() -> None:
    parser = argparse.ArgumentParser(description="xaynet-tpu coordinator")
    parser.add_argument("-c", "--config", help="TOML configuration file", default=None)
    args = parser.parse_args()
    settings = Settings.load(args.config)
    _pin_jax_platform()
    _enable_jax_compile_cache(settings)
    asyncio.run(serve(settings))


if __name__ == "__main__":
    main()
