"""Adaptive count/time windows: the round liveness controller.

A mis-sized deployment — ``count.min`` above the participant load a
population actually offers — fails every round forever: the window times
out, the Failure phase restarts the round, and the same too-high threshold
times out again. The :class:`RoundController` closes that loop. It observes
every phase's request-window outcome (accepted arrivals, full / degraded /
timeout, seconds in phase) and, across rounds, re-sizes the NEXT round's
``count.min`` and ``time.max`` within hard bounds:

- **shrink** after ``liveness.shrink_after`` consecutive non-full rounds
  (degraded or failed): ``count.min`` drops toward what the deployment
  demonstrably offers — ``min(count.min * shrink_factor, max observed
  arrivals)`` — never below the protocol floor (or the configured quorum),
  and ``time.max`` is relaxed by ``time_relax_factor`` up to
  ``time_max_ceil_s`` so stragglers get a longer window;
- **regrow** after ``liveness.grow_after`` consecutive full rounds:
  ``count.min`` climbs back by ``grow_factor``, never past the originally
  configured ``count.min`` (the operator's intent is the ceiling). When
  the observed arrivals exceed the current ``min`` (possible while
  ``time.min`` keeps the window open toward ``count.max``) they cap the
  step too; an observation EQUAL to ``min`` is censored — the window
  closes the moment ``min`` is reached, so it says nothing about headroom
  — and the controller probes upward anyway, relying on the shrink streak
  to take back an overshoot (AIMD-style). ``time.max`` decays back toward
  its configured value, floored by the window durations recently observed.

The two streak counters are the hysteresis: one lucky full round resets
the shrink streak (and vice versa), so the windows converge instead of
oscillating on noisy arrivals. Every adjustment is logged, counted on
``xaynet_liveness_adjustments_total{phase,direction}`` and visible on the
``xaynet_count_min{phase}`` gauge.

The controller mutates the live ``Settings.pet`` sections in place — the
phases re-read them at every window, and Idle persists coordinator state
(not settings), so adjustments are process-local and reset on restart.
"""

from __future__ import annotations

import logging
import math
from collections import deque

from ..core.message import SUM_COUNT_MIN, UPDATE_COUNT_MIN
from ..telemetry.registry import get_registry
from .settings import Settings

logger = logging.getLogger("xaynet.coordinator")

_registry = get_registry()
ADJUSTMENTS = _registry.counter(
    "xaynet_liveness_adjustments_total",
    "Round-controller count-window adjustments, by phase and direction.",
    ("phase", "direction"),
)
COUNT_MIN = _registry.gauge(
    "xaynet_count_min",
    "Effective per-phase count.min after controller adjustments.",
    ("phase",),
)
ROUND_OUTCOMES = _registry.counter(
    "xaynet_round_outcome_total",
    "Rounds finished, by outcome (full | degraded | failed).",
    ("outcome",),
)

_FLOORS = {"sum": SUM_COUNT_MIN, "update": UPDATE_COUNT_MIN, "sum2": SUM_COUNT_MIN}


class RoundController:
    """Hysteresis-driven re-sizing of the per-phase request windows."""

    def __init__(self, settings: Settings):
        self.settings = settings
        self.liveness = settings.liveness
        self._sections = {
            "sum": settings.pet.sum,
            "update": settings.pet.update,
            "sum2": settings.pet.sum2,
        }
        # the operator's configuration is the hard ceiling the controller
        # may never exceed (and the target regrowth converges back to)
        self._ceil_min = {n: s.count.min for n, s in self._sections.items()}
        self._orig_time_max = {n: s.time.max for n, s in self._sections.items()}
        self._floor = {
            n: max(_FLOORS[n], s.count.quorum or 0) for n, s in self._sections.items()
        }
        window = self.liveness.window
        self._arrivals: dict[str, deque] = {n: deque(maxlen=window) for n in self._sections}
        self._latency: dict[str, deque] = {n: deque(maxlen=window) for n in self._sections}
        self._full_streak = 0
        self._nonfull_streak = 0
        self._round_degraded = False
        for name, section in self._sections.items():
            COUNT_MIN.labels(phase=name).set(section.count.min)

    # --- observations (called by the phases) -------------------------------

    def observe_phase(self, phase: str, accepted: int, outcome: str, seconds: float) -> None:
        """One request window closed: record arrivals + latency and whether
        the round is still on a full-completion track."""
        if phase not in self._sections:
            return
        self._arrivals[phase].append(int(accepted))
        if outcome != "timeout" and seconds < self._sections[phase].time.max:
            # a window that burned its whole (possibly relaxed) time.max —
            # a timeout, or a degraded close that only fired because
            # time.max expired at quorum — measures the CEILING, not the
            # demand; recording it would floor the decay at the inflated
            # ceiling forever. Only windows that closed early tell us how
            # long rounds genuinely need.
            self._latency[phase].append(float(seconds))
        if outcome != "full":
            self._round_degraded = True

    def round_completed(self) -> None:
        """The round reached Unmask successfully (Idle is next)."""
        outcome = "degraded" if self._round_degraded else "full"
        ROUND_OUTCOMES.labels(outcome=outcome).inc()
        if self._round_degraded:
            self._nonfull()
        else:
            self._full_streak += 1
            self._nonfull_streak = 0
            if self._full_streak >= self.liveness.grow_after:
                self._full_streak = 0
                self._grow()
        self._round_degraded = False

    def round_failed(self) -> None:
        """The round died in Failure (timeout or infrastructure error)."""
        ROUND_OUTCOMES.labels(outcome="failed").inc()
        self._nonfull()
        self._round_degraded = False

    def _nonfull(self) -> None:
        self._nonfull_streak += 1
        self._full_streak = 0
        if self._nonfull_streak >= self.liveness.shrink_after:
            self._nonfull_streak = 0
            self._shrink()

    # --- adjustments --------------------------------------------------------

    def _shrink(self) -> None:
        for name, section in self._sections.items():
            count = section.count
            if not self._arrivals[name]:
                continue  # never observed (an earlier phase starved first)
            # judge by the FAILING streak only: readings from the healthy
            # era before the load dropped would mask the starved phase for
            # up to `window` thrown-away rounds
            recent = list(self._arrivals[name])[-self.liveness.shrink_after:]
            observed = max(recent)
            if observed >= count.min:
                continue  # this phase meets its window; it isn't the problem
            target = min(
                math.floor(count.min * self.liveness.shrink_factor), observed
            )
            new_min = max(self._floor[name], target)
            if new_min >= count.min:
                # factor/observed didn't move it: step down by one so a
                # repeatedly-failing deployment still converges to the floor
                new_min = max(self._floor[name], count.min - 1)
            new_time = min(
                self.liveness.time_max_ceil_s,
                section.time.max * self.liveness.time_relax_factor,
            )
            if new_min == count.min and new_time == section.time.max:
                continue
            logger.warning(
                "liveness: shrinking %s window — count.min %d -> %d "
                "(observed arrivals %d, floor %d), time.max %.1fs -> %.1fs",
                name, count.min, new_min, observed, self._floor[name],
                section.time.max, new_time,
            )
            self._apply(name, new_min, new_time, "shrink")

    def _grow(self) -> None:
        for name, section in self._sections.items():
            count = section.count
            if not self._arrivals[name]:
                continue
            observed = max(self._arrivals[name])
            target = min(
                self._ceil_min[name],
                max(count.min + 1, math.ceil(count.min * self.liveness.grow_factor)),
            )
            if observed > count.min:
                # the window saw MORE than it demanded (time.min > 0 lets
                # accepted run past min toward max): a true load reading —
                # no point regrowing past it
                target = min(target, observed)
            # else the reading is CENSORED at count.min (the window closes
            # the moment min is reached), so it says nothing about headroom:
            # probe upward anyway — an overshoot degrades a few rounds and
            # the shrink streak takes it right back (AIMD-style)
            new_min = max(count.min, target)
            new_time = max(
                self._orig_time_max[name],
                section.time.max / self.liveness.time_relax_factor,
                # never decay below what recent windows demonstrably took —
                # cutting under the observed duration would re-induce the
                # very timeouts the relax was for
                max(self._latency[name], default=0.0),
            )
            if new_min == count.min and new_time == section.time.max:
                continue
            logger.info(
                "liveness: regrowing %s window — count.min %d -> %d "
                "(observed arrivals %d, ceiling %d), time.max %.1fs -> %.1fs",
                name, count.min, new_min, observed, self._ceil_min[name],
                section.time.max, new_time,
            )
            self._apply(name, new_min, new_time, "grow")

    def _apply(self, name: str, new_min: int, new_time: float, direction: str) -> None:
        section = self._sections[name]
        section.count.min = new_min
        # count.quorum <= min is re-established by CountSettings.
        # effective_quorum when the phase window reads it; time.min <=
        # time.max stays true because time.max only moves within
        # [configured, ceil] and configured was already valid
        section.time.max = new_time
        ADJUSTMENTS.labels(phase=name, direction=direction).inc()
        COUNT_MIN.labels(phase=name).set(new_min)
