"""Message-processing pipeline and data fetchers.

Functional port of the reference's tower service stack (reference:
rust/xaynet-server/src/services/messages/mod.rs:30-118):

    Decryptor -> MessageParser (phase filter + signature verification)
    -> MultipartHandler (chunk reassembly) -> TaskValidator -> StateMachine

CPU-heavy stages (sealed-box open, Ed25519 verify) run on a thread pool so
the asyncio loop stays responsive — the analogue of the reference's rayon
offload with a concurrency limit.

``Fetcher`` exposes the latest event-bus values to the API layer
(reference: rust/xaynet-server/src/services/fetchers/mod.rs:27-42).
"""

from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..core.common import RoundParameters
from ..core.crypto.encrypt import DecryptError, EncryptKeyPair
from ..core.crypto.sign import is_eligible, verify_detached
from ..core.mask.serialization import DecodeError
from ..core.message import Chunk, Message, Sum, Sum2, Tag, Update, peek_header
from ..core.message.encoder import MessageBuilder
from ..telemetry.registry import get_registry
from ..utils import tracing
from .events import EventSubscriber, PhaseName
from .requests import RequestSender, request_from_message

_PHASE_TAGS = {
    PhaseName.SUM: Tag.SUM,
    PhaseName.UPDATE: Tag.UPDATE,
    PhaseName.SUM2: Tag.SUM2,
}

# ms-scale crypto stages; the 'total' series includes the state-machine wait
_PIPELINE_SECONDS = get_registry().histogram(
    "xaynet_message_pipeline_seconds",
    "Message-pipeline stage wall time (decrypt_parse = sealed-box open + "
    "signature verify on the thread pool; total = end-to-end handling).",
    ("stage",),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
)
_MULTIPART_BUFFERS = get_registry().gauge(
    "xaynet_multipart_buffers",
    "Multipart reassembly buffers currently held (bounded, oldest-evicted).",
)


class ServiceError(Exception):
    """A message was dropped by the pipeline (with the stage as context)."""

    def __init__(self, stage: str, detail: str):
        super().__init__(f"{stage}: {detail}")
        self.stage = stage


class PetMessageHandler:
    """End-to-end handling of one encrypted PET message."""

    def __init__(
        self,
        events: EventSubscriber,
        request_tx: RequestSender,
        max_workers: int = 4,
        wire_ingest: bool = False,
    ):
        self.events = events
        self.request_tx = request_tx
        # device-ingest coordinators parse Update masked models LAZILY (raw
        # element block kept; unpack + validity run on the accelerator in
        # validate_aggregation, before the seed-dict insert)
        self.wire_ingest = wire_ingest
        self._pool = ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="pet-msg")
        # multipart reassembly buffers keyed by (participant_pk, message_id);
        # bounded: abandoned reassemblies are evicted oldest-first so a
        # client cannot grow coordinator memory without completing messages
        self._multipart: dict[tuple[bytes, int], MessageBuilder] = {}
        self.max_multipart_buffers = 4096

    async def handle_message(self, encrypted: bytes) -> None:
        """Decrypt, verify, validate and forward one message.

        Raises ``ServiceError`` (pipeline drop) or ``RequestError`` (state
        machine rejection).
        """
        tracing.new_request_id()
        with tracing.span("handle_message", size=len(encrypted)):
            with _PIPELINE_SECONDS.labels(stage="total").time():
                with _PIPELINE_SECONDS.labels(stage="decrypt_parse").time():
                    message = await self._parse_message(encrypted)
                if message is None:
                    return  # multipart message still incomplete
                with tracing.span("task_validator"):
                    self._validate_task(message)
                await self.request_tx.request(request_from_message(message))

    # --- pipeline stages --------------------------------------------------

    def _decrypt_parse_one(
        self, encrypted: bytes, keys: EncryptKeyPair, phase: PhaseName
    ) -> Message:
        """Sealed-box open + phase filter + signature verify + parse.

        Synchronous CPU body shared by the per-message path and the batched
        ingest workers; always runs on a worker thread.
        """
        # sealed-box open (CPU) — reference: decryptor.rs:48-69. Passing our
        # public key skips a per-message X25519 recompute of it (milliseconds
        # per message on the pure-python fallback)
        try:
            raw = keys.secret.decrypt(encrypted, keys.public)
        except (DecryptError, ValueError) as e:
            raise ServiceError("decrypt", str(e)) from e
        # phase filter before the expensive signature check
        # (reference: message_parser.rs:88-141)
        try:
            _, tag, _ = peek_header(raw)
        except DecodeError as e:
            raise ServiceError("parse", str(e)) from e
        expected = _PHASE_TAGS.get(phase)
        if expected is None or tag != expected:
            # the tag rides in the decrypted header, so the taint pass sees
            # plaintext-derived bytes here — but a message-type enum name is
            # a one-byte projection, not key material
            raise ServiceError(  # lint: taint-ok: one-byte message-type tag, not key bytes
                "phase-filter", f"{tag.name} message during {phase.value}"
            )
        # signature verification + full parse
        try:
            return Message.from_bytes(raw, verify=True, lazy_update_vect=self.wire_ingest)
        except DecodeError as e:
            raise ServiceError("parse", str(e)) from e

    async def _parse_message(self, encrypted: bytes) -> Optional[Message]:
        loop = asyncio.get_running_loop()
        keys: EncryptKeyPair = self.events.keys.get_latest().event
        phase: PhaseName = self.events.phase.get_latest().event
        message = await loop.run_in_executor(
            self._pool, self._decrypt_parse_one, encrypted, keys, phase
        )
        if message.is_multipart:
            return self._handle_chunk(message)
        return message

    async def process_batch(self, batch: list[bytes]) -> list:
        """Decrypt + verify + task-validate a whole batch in ONE thread-pool
        hop (the ingest workers' entry point).

        Returns one slot per input, aligned: a verified ``Message``, a
        ``ServiceError`` (the drop, with its stage), or ``None`` (multipart
        chunk absorbed, message still incomplete). Unlike
        ``handle_message`` nothing is forwarded to the state machine — the
        caller owns request submission and batching policy.
        """
        loop = asyncio.get_running_loop()
        keys: EncryptKeyPair = self.events.keys.get_latest().event
        phase: PhaseName = self.events.phase.get_latest().event
        params: RoundParameters = self.events.params.get_latest().event

        def run() -> list:
            out = []
            for encrypted in batch:
                try:
                    message = self._decrypt_parse_one(encrypted, keys, phase)
                    if not message.is_multipart:
                        self._validate_task_with(message, params)
                    out.append(message)
                except ServiceError as e:
                    out.append(e)
            return out

        with _PIPELINE_SECONDS.labels(stage="decrypt_parse_batch").time():
            results = await loop.run_in_executor(self._pool, run)
        final = []
        for res in results:
            if isinstance(res, ServiceError) or res is None or not res.is_multipart:
                final.append(res)
                continue
            # multipart reassembly state is loop-owned — finish on the loop
            try:
                message = self._handle_chunk(res)
                if message is not None:
                    self._validate_task_with(message, params)
                final.append(message)
            except ServiceError as e:
                final.append(e)
        return final

    def _handle_chunk(self, message: Message) -> Optional[Message]:
        """Reassembly per (participant, message_id)
        (reference: multipart/service.rs:26-117)."""
        chunk = message.payload
        assert isinstance(chunk, Chunk)
        key = (message.participant_pk, chunk.message_id)
        if key not in self._multipart and len(self._multipart) >= self.max_multipart_buffers:
            evicted = next(iter(self._multipart))
            del self._multipart[evicted]
        builder = self._multipart.setdefault(key, MessageBuilder())
        _MULTIPART_BUFFERS.set(len(self._multipart))
        if not builder.add(chunk):
            return None
        del self._multipart[key]
        _MULTIPART_BUFFERS.set(len(self._multipart))
        # streaming parse: chunk buffers are consumed as the parser reads,
        # never concatenated (reference: multipart/service.rs streaming
        # FromBytes re-parse; chunkable_iterator.rs:17-60)
        from ..core.message.payloads import parse_payload_stream

        try:
            payload = parse_payload_stream(
                message.tag, builder.take_reader(), lazy_update_vect=self.wire_ingest
            )
        except DecodeError as e:
            raise ServiceError("multipart", str(e)) from e
        return Message(
            participant_pk=message.participant_pk,
            coordinator_pk=message.coordinator_pk,
            payload=payload,
            tag=message.tag,
            is_multipart=False,
            signature=message.signature,
        )

    def _validate_task(self, message: Message) -> None:
        """Sum/update task eligibility (reference: task_validator.rs:40-88)."""
        self._validate_task_with(message, self.events.params.get_latest().event)

    @staticmethod
    def _validate_task_with(message: Message, params: RoundParameters) -> None:
        """Pure-compute validation body (thread-safe; params pre-fetched)."""
        seed = params.seed.as_bytes()
        payload = message.payload
        if isinstance(payload, (Sum, Sum2)):
            if not verify_detached(message.participant_pk, payload.sum_signature, seed + b"sum"):
                raise ServiceError("task-validator", "invalid sum task signature")
            if not is_eligible(payload.sum_signature, params.sum):
                raise ServiceError("task-validator", "not eligible for the sum task")
        elif isinstance(payload, Update):
            if not verify_detached(message.participant_pk, payload.sum_signature, seed + b"sum"):
                raise ServiceError("task-validator", "invalid sum task signature")
            if not verify_detached(
                message.participant_pk, payload.update_signature, seed + b"update"
            ):
                raise ServiceError("task-validator", "invalid update task signature")
            # an update participant must NOT be a sum participant, and must
            # be eligible for the update task
            if is_eligible(payload.sum_signature, params.sum):
                raise ServiceError("task-validator", "sum participant sent an update message")
            if not is_eligible(payload.update_signature, params.update):
                raise ServiceError("task-validator", "not eligible for the update task")
        else:
            raise ServiceError("task-validator", f"unexpected payload {type(payload)}")


class Fetcher:
    """Read access to the latest round data for the API layer."""

    def __init__(self, events: EventSubscriber):
        self.events = events

    def round_params(self) -> RoundParameters:
        return self.events.params.get_latest().event

    def phase(self) -> PhaseName:
        return self.events.phase.get_latest().event

    def sum_dict(self):
        return self.events.sum_dict.get_latest().event.dict

    def seed_dict(self):
        return self.events.seed_dict.get_latest().event.dict

    def seeds_for(self, pk: bytes):
        """The UpdateSeedDict slice for one sum participant (GET /seeds)."""
        seed_dict = self.seed_dict()
        if seed_dict is None:
            return None
        return seed_dict.get(pk)

    def model(self):
        return self.events.model.get_latest().event.model
