"""Watch-channel event bus between the state machine and the services.

Functional port of the reference's event system (reference:
rust/xaynet-server/src/state_machine/events.rs:17-247): the state machine is
the single writer; services read the *latest* value of each channel
(round-id-stamped) without consuming it, and can await changes. Built on
asyncio's single-loop execution (no locks needed).
"""

from __future__ import annotations

import asyncio
import copy
from dataclasses import dataclass
from enum import Enum
from typing import Generic, Optional, TypeVar

from ..core.common import RoundParameters

T = TypeVar("T")


class PhaseName(str, Enum):
    IDLE = "idle"
    SUM = "sum"
    UPDATE = "update"
    SUM2 = "sum2"
    UNMASK = "unmask"
    FAILURE = "failure"
    SHUTDOWN = "shutdown"


@dataclass
class Event(Generic[T]):
    """A round-stamped event value."""

    round_id: int
    event: T


class ModelUpdate:
    """Latest global model announcement: invalidated or a new model."""

    __slots__ = ("model",)

    def __init__(self, model=None):
        self.model = model  # None == Invalidate

    @classmethod
    def invalidate(cls) -> "ModelUpdate":
        return cls(None)

    @classmethod
    def new(cls, model) -> "ModelUpdate":
        return cls(model)


class DictionaryUpdate:
    """Latest dictionary announcement: invalidated or a new dictionary."""

    __slots__ = ("dict",)

    def __init__(self, value=None):
        self.dict = value

    @classmethod
    def invalidate(cls) -> "DictionaryUpdate":
        return cls(None)

    @classmethod
    def new(cls, value) -> "DictionaryUpdate":
        return cls(value)


class _Watch(Generic[T]):
    """Single-writer watch cell: latest value + change notification."""

    def __init__(self, initial: Event):
        self._latest: Event = initial
        self._changed = asyncio.Event()

    def publish(self, event: Event) -> None:
        self._latest = event
        self._changed.set()
        self._changed = asyncio.Event()

    def get_latest(self) -> Event:
        return self._latest

    async def changed(self) -> Event:
        await self._changed.wait()
        return self._latest


class EventPublisher:
    """The state machine's writing end of the event bus."""

    def __init__(
        self,
        round_id: int,
        keys,
        params: RoundParameters,
        phase: PhaseName,
        model: Optional[ModelUpdate] = None,
    ):
        self._round_id = round_id
        self.keys = _Watch(Event(round_id, keys))
        # round_params is mutated in place by the Idle phase; events must
        # carry snapshots so subscribers can detect changes
        self.params = _Watch(Event(round_id, copy.copy(params)))
        self.phase = _Watch(Event(round_id, phase))
        self.model = _Watch(Event(round_id, model or ModelUpdate.invalidate()))
        self.sum_dict = _Watch(Event(round_id, DictionaryUpdate.invalidate()))
        self.seed_dict = _Watch(Event(round_id, DictionaryUpdate.invalidate()))

    def set_round_id(self, round_id: int) -> None:
        self._round_id = round_id

    @property
    def round_id(self) -> int:
        return self._round_id

    def broadcast_keys(self, keys) -> None:
        self.keys.publish(Event(self._round_id, keys))

    def broadcast_params(self, params: RoundParameters) -> None:
        self.params.publish(Event(self._round_id, copy.copy(params)))

    def broadcast_phase(self, phase: PhaseName) -> None:
        self.phase.publish(Event(self._round_id, phase))

    def broadcast_model(self, update: ModelUpdate) -> None:
        self.model.publish(Event(self._round_id, update))

    def broadcast_sum_dict(self, update: DictionaryUpdate) -> None:
        self.sum_dict.publish(Event(self._round_id, update))

    def broadcast_seed_dict(self, update: DictionaryUpdate) -> None:
        self.seed_dict.publish(Event(self._round_id, update))

    def subscribe(self) -> "EventSubscriber":
        return EventSubscriber(self)


class EventSubscriber:
    """Read-only view of the event bus (cloneable/shareable)."""

    def __init__(self, publisher: EventPublisher):
        self._pub = publisher

    @property
    def keys(self) -> _Watch:
        return self._pub.keys

    @property
    def params(self) -> _Watch:
        return self._pub.params

    @property
    def phase(self) -> _Watch:
        return self._pub.phase

    @property
    def model(self) -> _Watch:
        return self._pub.model

    @property
    def sum_dict(self) -> _Watch:
        return self._pub.sum_dict

    @property
    def seed_dict(self) -> _Watch:
        return self._pub.seed_dict
