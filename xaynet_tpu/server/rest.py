"""Coordinator REST API.

Functional port of the reference's HTTP surface (reference:
rust/xaynet-server/src/rest.rs:40-315):

- ``POST /message`` — opaque sealed-box message bytes
- ``GET /params``   — current round parameters
- ``GET /sums``     — sum dictionary (204 while absent)
- ``GET /seeds?pk=<hex>`` — a sum participant's seed slice (204 while absent)
- ``GET /model``    — latest global model bytes (204 while absent)
- ``GET /metrics``  — telemetry registry, Prometheus text exposition
- ``GET /healthz``  — liveness JSON (status, phase, round id, uptime)
- ``GET /statusz``  — live operator console, self-contained HTML (§20)
- ``GET /alerts``   — SLO engine state: active alerts + transition ring

Responses are JSON (parameters, dictionaries) or raw bytes (model) — a
readable stand-in for the reference's bincode bodies; both ends of the wire
are this framework. Implemented directly on asyncio streams (no third-party
HTTP dependency); optional TLS via ``ssl.SSLContext``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import ssl
import time
from dataclasses import dataclass
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..telemetry import tracing as trace
from ..telemetry.registry import MetricsRegistry, get_registry
from .requests import RequestError
from .services import Fetcher, PetMessageHandler, ServiceError

logger = logging.getLogger("xaynet.rest")


@dataclass
class TenantRoutes:
    """One tenant's REST surface: what ``/t/<tenant>/...`` dispatches to.

    The default tenant's routes double as the bare legacy paths
    (``/params`` == ``/t/<default>/params``), so single-tenant deployments
    and old SDKs keep working unchanged (docs/DESIGN.md §19).
    """

    fetcher: Fetcher
    handler: PetMessageHandler
    pipeline: object = None  # ingest.IngestPipeline
    edge_api: object = None  # edge.api.EdgeCoordinatorApi
    health_extra: object = None  # zero-arg callable merged into /healthz

MAX_BODY = 1 << 32  # u32 length field ceiling, as in the reference

SPAN_REQUEST = trace.declare_span("rest.request")

# polled endpoints are untraced: monitoring (/metrics, /healthz) and the
# round-state reads the SDK polls every tick (/params at tens of Hz in a
# soak, /sums and /seeds while waiting for dictionaries). Their spans
# would crowd the bounded round buffer and — because the buffer drops the
# NEWEST spans at its cap — could evict the end-of-round phase spans the
# CI validator requires. The causal story lives in the traced writes:
# POST /message and the /edge/* hops.
_UNTRACED_PATHS = {
    "/metrics", "/health", "/healthz", "/params", "/sums", "/seeds", "/model",
    "/statusz", "/alerts",
}

# known routes/methods keep the http counter's labels closed-cardinality —
# both tokens are attacker-controlled, and every distinct label value is a
# permanent registry child
_KNOWN_PATHS = {"/message", "/params", "/sums", "/seeds", "/model",
                "/health", "/healthz", "/metrics", "/statusz", "/alerts",
                "/edge/round", "/edge/envelope", "/admin/tenants"}
_KNOWN_METHODS = {"GET", "POST", "HEAD", "PUT", "DELETE", "OPTIONS", "PATCH"}


class RestServer:
    def __init__(
        self,
        fetcher: Fetcher,
        handler: PetMessageHandler,
        read_timeout: float = 120.0,
        registry: Optional[MetricsRegistry] = None,
        pipeline=None,
        edge_api=None,
        health_extra=None,
        tenants: Optional[dict[str, TenantRoutes]] = None,
        lifecycle=None,
        admin_token: str = "",
        default_tenant: str = "",
    ):
        # `registry` selects what GET /metrics renders. Hot-path modules
        # (request queue, message pipeline, kernel profiling, dispatcher)
        # record into the PROCESS registry at import time, so a custom
        # registry exposes only the families created against it (unit
        # tests); production keeps the default.
        # `pipeline` (ingest.IngestPipeline) switches POST /message to the
        # admission-controlled path: 429 + Retry-After under saturation, and
        # /healthz gains the intake section. None keeps the direct path.
        # `edge_api` (edge.api.EdgeCoordinatorApi) serves the edge tier:
        # GET /edge/round (round params + round keys for trusted edges) and
        # POST /edge/envelope (partial-aggregate intake).
        # `health_extra` is a zero-arg callable whose dict is merged into
        # the /healthz payload (the edge runner reports its upstream link
        # and envelope backlog through this hook).
        # `tenants` maps tenant id -> TenantRoutes for /t/<tenant>/...
        # routing; the positional args above stay the DEFAULT tenant (and
        # the bare legacy routes). None = single-tenant, as before.
        # `lifecycle` (tenancy.TenantLifecycle) turns the tenant set
        # elastic: mutating traffic consults its admission verdicts
        # (draining / quarantined tenants shed with 429) and `admin_token`
        # enables the authenticated /admin/tenants surface (constant-time
        # compare, like the edge tier; "" keeps it fully disabled).
        # `default_tenant` is the real id behind the bare legacy routes so
        # lifecycle admission applies to them too.
        self.fetcher = fetcher
        self.handler = handler
        self.pipeline = pipeline
        self.edge_api = edge_api
        self.health_extra = health_extra
        self._default_routes = TenantRoutes(
            fetcher=fetcher,
            handler=handler,
            pipeline=pipeline,
            edge_api=edge_api,
            health_extra=health_extra,
        )
        # the lifecycle manager mutates this dict at runtime (onboard
        # registers, offboard pops) — it must stay the SAME object the
        # manager holds, so adopt a provided dict instead of copying it
        self.tenants: dict[str, TenantRoutes] = (
            tenants if tenants is not None else {}
        )
        self.lifecycle = lifecycle
        self.admin_token = admin_token
        self.default_tenant = default_tenant
        self.read_timeout = read_timeout  # slow-client defense
        self.registry = registry if registry is not None else get_registry()
        self._started_at = time.monotonic()
        self._http_requests = self.registry.counter(
            "xaynet_http_requests_total",
            "REST requests by method, route, status code and tenant "
            "('' = the bare single-tenant routes).",
            ("method", "path", "status", "tenant"),
        )
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(
        self, host: str = "127.0.0.1", port: int = 8081, tls: Optional[ssl.SSLContext] = None
    ) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._handle_conn, host, port, ssl=tls)
        addr = self._server.sockets[0].getsockname()
        logger.info("REST API listening on %s:%d", addr[0], addr[1])
        return addr[0], addr[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # --- request handling -------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                request_line = await asyncio.wait_for(reader.readline(), self.read_timeout)
                if not request_line:
                    break
                try:
                    method, target, _ = request_line.decode().split(None, 2)
                except ValueError:
                    await self._respond(writer, 400, b"bad request")
                    break
                headers = {}
                while True:
                    line = await asyncio.wait_for(reader.readline(), self.read_timeout)
                    if line in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = line.decode().partition(":")
                    headers[name.strip().lower()] = value.strip()
                length = int(headers.get("content-length", "0"))
                if length > MAX_BODY:
                    await self._respond(writer, 413, b"body too large")
                    break
                body = (
                    await asyncio.wait_for(reader.readexactly(length), self.read_timeout)
                    if length
                    else b""
                )
                keep_alive = headers.get("connection", "keep-alive").lower() != "close"
                status, payload, ctype, extra = await self._route(method, target, body, headers)
                await self._respond(writer, status, payload, ctype, keep_alive, extra)
                if not keep_alive:
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError, asyncio.TimeoutError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:  # lint: swallow-ok (best-effort socket teardown)
                pass

    def _resolve_tenant(self, path: str):
        """Split a ``/t/<tenant>/<sub>`` target into (tenant id, sub path,
        routes); bare paths resolve to the default tenant's routes with an
        empty tenant label. Unknown tenants resolve to ``routes=None``."""
        if path != "/t" and not path.startswith("/t/"):
            return "", path, self._default_routes
        parts = path.split("/", 3)  # ["", "t", tenant, rest]
        tid = parts[2] if len(parts) > 2 else ""
        routes = self.tenants.get(tid)
        sub = "/" + (parts[3] if len(parts) > 3 else "")
        return tid, sub, routes

    async def _route(self, method: str, target: str, body: bytes, headers=None):
        url = urlparse(target)
        headers = headers or {}
        if url.path == "/admin/tenants" or url.path.startswith("/admin/tenants/"):
            status, payload, ctype, extra = await self._admin_route(
                method, url.path, body, headers
            )
            self._http_requests.labels(
                method=method if method in _KNOWN_METHODS else "other",
                path="/admin/tenants",  # subpath ids stay out of the labels
                status=status,
                tenant="",
            ).inc()
            return status, payload, ctype, extra
        tenant, path, routes = self._resolve_tenant(url.path)
        if routes is None:
            # unknown tenant: closed-cardinality labels (the id is
            # attacker-controlled), no dispatch
            self._http_requests.labels(
                method=method if method in _KNOWN_METHODS else "other",
                path="other",
                status=404,
                tenant="other",
            ).inc()
            return 404, b"unknown tenant", "text/plain", None
        # elastic-lifecycle admission (docs/DESIGN.md §23): a draining or
        # quarantined tenant's MUTATING traffic sheds at the door with 429
        # (GET polls stay served — a draining tenant's in-flight round
        # still needs its participants to fetch params/sums/seeds)
        if (
            self.lifecycle is not None
            and method == "POST"
            and path in ("/message", "/edge/envelope")
        ):
            admitted, retry_after = self.lifecycle.admit(
                tenant or self.default_tenant
            )
            if not admitted:
                extra = (
                    {"Retry-After": str(max(1, math.ceil(retry_after)))}
                    if retry_after
                    else None
                )
                self._http_requests.labels(
                    method=method,
                    path=path,
                    status=429,
                    tenant=tenant,
                ).inc()
                return 429, b"tenant not accepting traffic", "text/plain", extra
        # handlers return (status, payload, ctype) or + an extra-headers dict
        if path in _UNTRACED_PATHS:
            result = await self._dispatch(method, path, url.query, body, headers, routes)
        else:
            # the request span adopts the caller's trace (X-Xaynet-Trace:
            # SDK / edge hop) and sets the ambient context, so the ingest
            # admission span below lands in the same trace
            remote = trace.parse_header(headers.get(trace.TRACE_HEADER.lower()))
            with trace.get_tracer().span(
                SPAN_REQUEST, link=remote, method=method, path=path, tenant=tenant
            ) as span:
                result = await self._dispatch(method, path, url.query, body, headers, routes)
                span.set(status=result[0])
        status, payload, ctype = result[:3]
        extra = result[3] if len(result) > 3 else None
        self._http_requests.labels(
            method=method if method in _KNOWN_METHODS else "other",
            path=path if path in _KNOWN_PATHS else "other",
            status=status,
            # tenant ids come from the operator's [tenancy] config (a
            # validated closed set), never from the wire: unknown ids
            # bounced above with tenant="other"
            tenant=tenant,
        ).inc()
        return status, payload, ctype, extra

    async def _dispatch(self, method: str, path: str, query: str, body: bytes,
                        headers, routes: TenantRoutes):
        try:
            if method == "POST" and path == "/message":
                return await self._post_message(body, routes)
            if routes.edge_api is not None and path.startswith("/edge/"):
                return await self._edge_route(method, path, body, headers or {}, routes)
            if method == "GET" and path == "/params":
                return 200, json.dumps(routes.fetcher.round_params().to_dict()).encode(), "application/json"
            if method == "GET" and path == "/sums":
                sums = routes.fetcher.sum_dict()
                if sums is None:
                    return 204, b"", "text/plain"
                return (
                    200,
                    json.dumps({k.hex(): v.hex() for k, v in sums.items()}).encode(),
                    "application/json",
                )
            if method == "GET" and path == "/seeds":
                qs = parse_qs(query)
                pk_hex = (qs.get("pk") or [""])[0]
                if not pk_hex:
                    return 400, b"missing pk", "text/plain"
                seeds = routes.fetcher.seeds_for(bytes.fromhex(pk_hex))
                if seeds is None:
                    return 204, b"", "text/plain"
                if (qs.get("fmt") or [""])[0] == "bin":
                    # batched binary fan-out (§21): 112 B/entry fixed
                    # frames, ~half the bytes of the hex-JSON shape — the
                    # response the loadgen fleet and new SDKs request
                    from ..core.mask.seed import pack_seed_entries

                    return 200, pack_seed_entries(seeds), "application/octet-stream"
                return (
                    200,
                    json.dumps({k.hex(): v.as_bytes().hex() for k, v in seeds.items()}).encode(),
                    "application/json",
                )
            if method == "GET" and path == "/metrics":
                return (
                    200,
                    self.registry.render().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            if method == "GET" and path == "/statusz":
                # live operator console (§20): rendered from registry /
                # timeline / SLO state only — no jax import on this path
                from .console import render_statusz

                return (
                    200,
                    render_statusz(self).encode(),
                    "text/html; charset=utf-8",
                )
            if method == "GET" and path == "/alerts":
                from ..telemetry.slo import get_engine

                return (
                    200,
                    json.dumps(get_engine().alerts_payload()).encode(),
                    "application/json",
                )
            if method == "GET" and path == "/healthz":
                # liveness + the coarse round position, cheap enough to poll
                payload = self._health_payload(routes)
                payload["status"] = "ok"
                payload["uptime_seconds"] = round(time.monotonic() - self._started_at, 3)
                if routes.pipeline is not None:
                    ingest = routes.pipeline.health()
                    # the ingress boundary gets its own top-level section
                    # (§21): acceptance rates, shard occupancy, wire mix
                    payload["ingress"] = ingest.pop("ingress", None)
                    payload["ingest"] = ingest
                    if ingest["saturated"]:
                        payload["status"] = "saturated"
                streaming = self._streaming_health()
                if streaming is not None:
                    payload["pipeline"] = streaming
                tenancy = self._tenancy_health()
                if tenancy is not None:
                    payload["tenancy"] = tenancy
                if routes.health_extra is not None:
                    # role-specific sections (the edge runner reports its
                    # upstream link + envelope backlog here); an extra
                    # "status" key overrides ok (e.g. upstream unreachable)
                    payload.update(routes.health_extra())
                return 200, json.dumps(payload).encode(), "application/json"
            if method == "GET" and path == "/health":
                return 200, json.dumps(self._health_payload(routes)).encode(), "application/json"
            if method == "GET" and path == "/model":
                model = routes.fetcher.model()
                if model is None:
                    return 204, b"", "text/plain"
                # model DOWNLOAD response, not a request body
                body = np.asarray(model, np.float64).tobytes()  # lint: wirecopy-ok
                return 200, body, "application/octet-stream"
            return 404, b"not found", "text/plain"
        except Exception as err:
            logger.exception("request failed: %s %s", method, path)
            return 500, str(err).encode(), "text/plain"

    async def _admin_route(self, method: str, path: str, body: bytes, headers: dict):
        """The authenticated tenant-lifecycle surface (docs/DESIGN.md §23).

        - ``GET    /admin/tenants``        — lifecycle states of every tenant
        - ``POST   /admin/tenants``        — onboard: ``{"tenant": "<id>"}``
        - ``POST   /admin/tenants/<id>``   — reconfigure: ``{"weight", "tier"}``
        - ``DELETE /admin/tenants/<id>``   — graceful drain (+ hard-kill
          escalation after the drain budget)

        Fully disabled (404, indistinguishable from an unknown route)
        unless BOTH a lifecycle manager and a ``[tenancy] admin_token``
        are configured; the token check is constant-time like the edge
        tier's. Status mapping: 400 malformed id/body, 401 bad token, 409
        incompatible lifecycle state (already serving, not drainable).
        """
        import hmac

        if self.lifecycle is None or not self.admin_token:
            return 404, b"not found", "text/plain", None
        supplied = headers.get("x-admin-token", "")
        if not hmac.compare_digest(supplied.encode(), self.admin_token.encode()):
            return 401, b"bad admin token", "text/plain", None
        from ..tenancy import LifecycleError

        sub = path[len("/admin/tenants"):].strip("/")
        try:
            if method == "GET" and not sub:
                return (
                    200,
                    json.dumps({"tenants": self.lifecycle.states()}).encode(),
                    "application/json",
                    None,
                )
            if method == "POST" and not sub:
                spec = json.loads(body or b"{}")
                result = await self.lifecycle.onboard(str(spec.get("tenant", "")))
                return 200, json.dumps(result).encode(), "application/json", None
            if method in ("POST", "PATCH") and sub:
                spec = json.loads(body or b"{}")
                result = self.lifecycle.reconfigure(
                    sub, weight=spec.get("weight"), tier=spec.get("tier")
                )
                return 200, json.dumps(result).encode(), "application/json", None
            if method == "DELETE" and sub:
                result = await self.lifecycle.offboard(sub)
                return 200, json.dumps(result).encode(), "application/json", None
            return 404, b"not found", "text/plain", None
        except LifecycleError as err:
            return 409, str(err).encode(), "text/plain", None
        except (ValueError, KeyError) as err:  # bad tenant id / bad JSON body
            return 400, str(err).encode(), "text/plain", None
        except Exception as err:
            logger.exception("admin request failed: %s %s", method, path)
            return 500, str(err).encode(), "text/plain", None

    def _tenancy_health(self) -> dict | None:
        """The multi-tenant /healthz section: registered tenants, each
        tenant's phase/round, and the shared pool's page accounting.
        ``None`` (no section) for single-tenant deployments."""
        if not self.tenants:
            return None
        from ..tenancy.pool import get_pool

        return {
            "tenants": {
                tid: {
                    "phase": r.fetcher.phase().value,
                    "round_id": r.fetcher.events.params.get_latest().round_id,
                }
                for tid, r in self.tenants.items()
            },
            "pool": get_pool().stats(),
        }

    def _streaming_health(self) -> dict | None:
        """The streaming-fold ``pipeline`` section of /healthz, read from
        the telemetry registry (no jax import on the REST path): the
        global pipeline gauges plus, for shard-parallel folds, the
        per-shard staging depth / in-flight folds / overlap ratio keyed by
        shard index. ``None`` when no streaming pipeline ever ran in this
        process (host aggregation) — the section simply doesn't appear."""
        depth = self.registry.sample_value("xaynet_streaming_staging_depth")
        if depth is None:
            return None
        reg = self.registry
        section = {
            "staging_depth": depth,
            "inflight_folds": reg.sample_value("xaynet_streaming_inflight_folds") or 0,
            "overlap_ratio": reg.sample_value("xaynet_streaming_overlap_ratio") or 0.0,
            "degraded": bool(reg.sample_value("xaynet_streaming_degraded") or 0),
        }
        shards: dict[str, dict] = {}
        for metric, field in (
            ("xaynet_streaming_shard_staging_depth", "staging_depth"),
            ("xaynet_streaming_shard_inflight_folds", "inflight_folds"),
            ("xaynet_streaming_shard_overlap_ratio", "overlap_ratio"),
        ):
            family = reg.get(metric)
            if family is None:
                continue
            for key, child in family.children():
                shards.setdefault(key[0], {})[field] = child.value
        if shards:
            section["shards"] = {
                k: shards[k]
                for k in sorted(shards, key=lambda s: int(s) if s.isdigit() else -1)
            }
        return section

    async def _edge_route(self, method: str, path: str, body: bytes, headers: dict,
                          routes: TenantRoutes):
        """Edge-tier endpoints (served only with ``[edge] enabled = true``).

        Status mapping for POST /edge/envelope keeps the edge's retry
        decision unambiguous: 200 folded, 400 unparseable, 401 bad token,
        409 protocol rejection (PERMANENT — drop the envelope, its members
        fall back to uploading upstream directly), 503 the state machine
        could not take the request right now (transient — retry).
        """
        from ..edge.envelope import EnvelopeError

        edge_api = routes.edge_api
        if not edge_api.authorized(headers):
            return 401, b"bad edge token", "text/plain"
        if method == "GET" and path == "/edge/round":
            # the round handoff IS the protocol: a trusted edge needs the
            # round's secret key to act as the decrypt/verify tier (§11),
            # behind the constant-time token check above
            return (
                200,
                json.dumps(edge_api.round_info()).encode(),  # lint: taint-ok: edge round handoff
                "application/json",
            )
        if method == "POST" and path == "/edge/envelope":
            try:
                accepted, detail = await edge_api.submit_envelope(body)
            except EnvelopeError as err:
                return 400, f"bad envelope: {err}".encode(), "text/plain"
            except RequestError as err:
                # INTERNAL: channel closed / machine mid-transition — the
                # envelope was NOT folded; the edge retries it
                return 503, str(err).encode(), "text/plain", {"Retry-After": "1"}
            if not accepted:
                return 409, (detail or "envelope rejected").encode(), "text/plain"
            return 200, b"", "text/plain"
        return 404, b"not found", "text/plain"

    def _health_payload(self, routes: TenantRoutes) -> dict:
        """Shared by /health (legacy shape) and /healthz (superset)."""
        return {
            "phase": routes.fetcher.phase().value,
            "round_id": routes.fetcher.events.params.get_latest().round_id,
        }

    async def _post_message(self, body: bytes, routes: TenantRoutes):
        if routes.pipeline is not None:
            verdict = await routes.pipeline.submit(body)
            if verdict.shed:
                retry = str(max(1, math.ceil(verdict.retry_after)))
                return (
                    429,
                    b"intake saturated; retry later",
                    "text/plain",
                    {"Retry-After": retry},
                )
            # admitted (processed asynchronously) or pre-filter drop: both
            # answer 200 — the reference reports drops via round
            # progression, not the POST status
            return 200, b"", "text/plain"
        try:
            await routes.handler.handle_message(body)
        except (ServiceError, RequestError) as err:
            # the reference answers 200 regardless and logs the drop —
            # clients learn outcomes from round progression, not the POST
            logger.debug("message dropped: %s", err)
        return 200, b"", "text/plain"

    @staticmethod
    async def _respond(
        writer: asyncio.StreamWriter,
        status: int,
        payload: bytes,
        ctype: str = "text/plain",
        keep_alive: bool = False,
        extra_headers: Optional[dict] = None,
    ) -> None:
        reason = {
            200: "OK",
            204: "No Content",
            400: "Bad Request",
            401: "Unauthorized",
            404: "Not Found",
            409: "Conflict",
            413: "Payload Too Large",
            429: "Too Many Requests",
            500: "Internal Server Error",
            502: "Bad Gateway",
            503: "Service Unavailable",
        }.get(status, "")
        extra = "".join(
            f"{name}: {value}\r\n" for name, value in (extra_headers or {}).items()
        )
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(payload)}\r\n"
            f"{extra}"
            f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n\r\n"
        ).encode()
        writer.write(head + payload)
        await writer.drain()
