"""Idle phase: round bootstrap.

Reference behavior (rust/xaynet-server/src/state_machine/phases/idle.rs:41-151):
increment the round id, delete the previous round's dictionaries, generate a
fresh round encryption keypair, deterministically advance the round seed
(``seed = sha256(sign_ed25519(seed ‖ sum_prob_le ‖ update_prob_le))`` with a
signing key derived from the new encryption secret), persist the coordinator
state, then broadcast keys and parameters.
"""

from __future__ import annotations

import struct

from ...core.common import RoundSeed
from ...core.crypto.encrypt import EncryptKeyPair
from ...core.crypto.hash import sha256
from ...core.crypto.sign import SigningKeyPair
from ...telemetry import tracing as trace
from ..events import DictionaryUpdate, PhaseName
from .base import PhaseState, Shared


class Idle(PhaseState):
    NAME = PhaseName.IDLE

    def __init__(self, shared: Shared):
        super().__init__(shared)
        # events emitted early in the round must carry the new round id
        shared.set_round_id(shared.round_id + 1)
        if shared.metrics is not None:
            shared.metrics.round_total(shared.round_id)

    async def process(self) -> None:
        await self.shared.store.coordinator.delete_dicts()
        # the previous round's mid-round checkpoint (and its resume budget)
        # cannot outlive the dictionaries it is consistent with
        await self.shared.store.coordinator.delete_round_checkpoint()
        self.shared.resume_attempts = 0  # lint: tenant-ok: round reset within this tenant's own Shared
        # a stale graceful-flush hook would journal a dead phase's state
        self.shared.flush_hook = None
        self._reconcile_pool()
        # per-edge envelope watermarks are round-scoped: window sequences
        # restart at 0 with every round's fresh window state on the edges
        self.shared.edge_watermarks.clear()  # lint: tenant-ok: round reset within this tenant's own Shared
        self._gen_round_keypair()
        self._update_round_probabilities()
        self._update_round_seed()
        # the round's trace window opens HERE, the moment the new seed
        # exists: the trace id derives from it, so the SDK and the edge tier
        # compute the identical id from the broadcast parameters and the
        # whole distributed round stitches into one trace (DESIGN §16). The
        # previous round's trace flushes (Chrome export) as a side effect.
        trace.get_tracer().begin_round(
            self.shared.round_id,
            trace.round_trace_id(self.shared.state.round_params.seed.as_bytes()),
        )
        await self.shared.store.coordinator.set_coordinator_state(self.shared.state.to_bytes())

    def broadcast(self) -> None:
        self.shared.events.broadcast_keys(self.shared.state.keys)
        self.shared.events.broadcast_params(self.shared.state.round_params)
        # previous round's artefacts are no longer valid
        self.shared.events.broadcast_sum_dict(DictionaryUpdate.invalidate())
        self.shared.events.broadcast_seed_dict(DictionaryUpdate.invalidate())

    async def next(self):
        from .sum import SumPhase

        return SumPhase(self.shared)

    # --- internals --------------------------------------------------------

    def _reconcile_pool(self) -> None:
        """Round-boundary page accounting (docs/DESIGN.md §19): at Idle the
        tenant must hold ZERO pool leases — the previous round's unmask
        released them on the clean path. A crashed round (Failure -> Idle)
        leaks its aggregator's leases instead: run the GC so dropped plans'
        finalizers return their pages safely (the buffers are unreachable,
        nothing can alias them), then force-reclaim any stragglers, counted
        on ``xaynet_pool_reclaimed_total`` so the invariant break is
        visible on /metrics rather than silent."""
        from ...tenancy.pool import get_pool

        pool = get_pool()
        if not pool.balanced(self.shared.tenant):
            import gc

            gc.collect()
            pool.reclaim(self.shared.tenant)
        # between-round defrag (docs/DESIGN.md §23): Idle is the only phase
        # where this tenant holds no transient fold views, so compaction's
        # memmove-under-lock cannot race this tenant's kernels. Other
        # tenants' live runs are protected by the migrator protocol (only
        # quiescent, migrator-registered leases move).
        ten = getattr(self.shared.settings, "tenancy", None)
        if ten is not None and ten.defrag_enabled:
            if pool.fragmentation() > ten.defrag_threshold:
                pool.compact()

    def _gen_round_keypair(self) -> None:
        keys = EncryptKeyPair.generate()
        self.shared.state.keys = keys
        self.shared.state.round_params.pk = keys.public.as_bytes()

    def _update_round_probabilities(self) -> None:
        # constant probabilities; adaptive strategies plug in here
        pass

    def _update_round_seed(self) -> None:
        params = self.shared.state.round_params
        signing = SigningKeyPair.derive_from_seed(self.shared.state.keys.secret.as_bytes())
        signature = signing.sign(
            params.seed.as_bytes()
            + struct.pack("<d", params.sum)
            + struct.pack("<d", params.update)
        )
        params.seed = RoundSeed(sha256(signature.as_bytes()))
