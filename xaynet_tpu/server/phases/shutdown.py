"""Shutdown phase: close and drain the request channel.

Reference behavior
(rust/xaynet-server/src/state_machine/phases/shutdown.rs:23-33).
"""

from __future__ import annotations

from ..events import PhaseName
from ..requests import ChannelClosed, RequestError
from .base import PhaseState


class Shutdown(PhaseState):
    NAME = PhaseName.SHUTDOWN

    async def process(self) -> None:
        rx = self.shared.request_rx
        rx.close()
        while True:
            try:
                env = rx.try_recv()
            except ChannelClosed:
                break
            if env is None:
                break
            self._respond(env, RequestError(RequestError.Kind.INTERNAL, "shutting down"))

    async def run_phase(self):
        self._announce()
        await self.process()
        return None
