"""Update phase: collect and aggregate masked model updates.

Reference behavior
(rust/xaynet-server/src/state_machine/phases/update.rs:50-184): for each
accepted ``UpdateRequest``: validate the masked object against the
aggregation state, atomically insert the participant's encrypted seed dict
(validated against the sum dictionary), then aggregate the masked model.
Afterwards the seed dictionary is fetched and broadcast for sum
participants.

TPU-native difference: accepted updates are *staged* and folded in batches
by the ``StagedAggregator`` (host numpy kernels or the sharded device fold)
instead of a per-update big-int loop; validation and seed-dict ordering are
per-update exactly as in the reference.

Resilience: when ``[resilience] checkpoint_enabled`` is on, the phase
periodically persists the drained aggregate through the store
(``CheckpointManager``), and the phase can be constructed with
``resume_from`` — a validated :class:`RoundCheckpoint` — to re-enter the
round with the aggregate restored instead of restarting at Idle
(docs/DESIGN.md §9). A resumed phase's count window is reduced by the
restored updates, so an already-satisfied round drains straight through.
"""

from __future__ import annotations

import asyncio
import logging

from ...core.mask.masking import AggregationError
from ...resilience.chaos import maybe_kill
from ...resilience.checkpoint import CheckpointManager, RoundCheckpoint, entry, write_entry
from ...telemetry.registry import get_registry
from ..aggregation import StagedAggregator, build_staged_aggregator
from ..events import DictionaryUpdate, PhaseName
from ..requests import (
    EnvelopeReplay,
    PartialAggregate,
    RequestError,
    StateMachineRequest,
    UpdateRequest,
)
from .base import PhaseError, PhaseState, reduce_count_window

logger = logging.getLogger("xaynet.coordinator")

_registry = get_registry()
EDGE_ENVELOPES = _registry.counter(
    "xaynet_edge_envelopes_total",
    "Partial-aggregate envelopes handled by the update phase, by outcome "
    "(accepted | replay = already-folded envelope acked idempotently | "
    "stale = below the per-edge watermark | rejected).",
    ("outcome",),
)
EDGE_MEMBERS_FOLDED = _registry.counter(
    "xaynet_edge_members_folded_total",
    "Masked updates folded via accepted partial-aggregate envelopes.",
)


class UpdatePhase(PhaseState):
    NAME = PhaseName.UPDATE

    def __init__(self, shared, resume_from: RoundCheckpoint | None = None):
        super().__init__(shared)
        settings = shared.settings
        self.aggregator: StagedAggregator = build_staged_aggregator(shared)
        self._seed_dict = None
        self._resume_from = resume_from
        self._resumed_models = 0
        if resume_from is not None:
            if resume_from.nb_models:
                self.aggregator.restore_journal(resume_from)
            self._resumed_models = resume_from.nb_models
            # the restored updates count as arrivals for the liveness
            # controller: the post-resume window is offset by them, and
            # reporting only the remainder would poison the shrink clamp
            # with a tiny "observed load" (base.PhaseState.arrivals_offset)
            self.arrivals_offset = resume_from.nb_models
            logger.info(  # lint: taint-ok: restored-model COUNT only, no journal payload
                "round %d: update phase RESUMED from journal (%d models restored)",
                shared.round_id,
                resume_from.nb_models,
            )
        resilience = settings.resilience
        self._ckpt = (
            CheckpointManager(
                shared,
                self.aggregator,
                every_batches=resilience.checkpoint_every_batches,
                every_s=resilience.checkpoint_every_s,
            )
            if resilience.checkpoint_enabled
            else None
        )

    async def process(self) -> None:
        params = self.shared.settings.pet.update
        if self._resume_from is not None:
            # the restored updates already satisfied part of the window; a
            # fully-satisfied resume drains straight through to sum2 (the
            # participants who submitted them will not resend)
            params = reduce_count_window(params, self._resumed_models)
            # sum participants contacting a restarted coordinator need the
            # sum dictionary re-broadcast to build their seed dicts
            sum_dict = await self.shared.store.coordinator.sum_dict()
            if sum_dict:
                self.shared.events.broadcast_sum_dict(DictionaryUpdate.new(sum_dict))
        elif self._ckpt is not None:
            # seal the Sum -> Update transition: a crash before the first
            # accepted update must resume into Update with the frozen sum
            # dictionary, not restart the round from Idle
            sum_dict = await self.shared.store.coordinator.sum_dict() or {}
            await write_entry(self.shared, entry(self.shared, "update", sum_dict=sum_dict))
        if self._ckpt is not None:
            # graceful-signal flush: the journal cadence may lag the live
            # aggregate; a SIGTERM mid-phase forces one final save (runner)
            self.shared.flush_hook = self._ckpt.save_now
        await self.process_requests(params)
        if self.shared.settings.overlap.feature("sum2_drain"):
            # phase overlap (docs/DESIGN.md §22): SUBMIT the staged
            # remainder but leave the drain barrier to the sum2 phase,
            # which runs it in the background under its own collection
            # wall — the fold tail that used to extend the update wall is
            # hidden, and fold errors still fail the round before Unmask
            await asyncio.get_running_loop().run_in_executor(None, self.aggregator.flush)
        else:
            # phase transition: drain the streaming pipeline — every
            # submitted fold completes and the deferred acceptance sync
            # runs, off the event loop (the one blocking sync point)
            await asyncio.get_running_loop().run_in_executor(None, self.aggregator.drain)
        self._seed_dict = await self.shared.store.coordinator.seed_dict()
        if not self._seed_dict:
            raise PhaseError("NoSeedDict", "seed dictionary missing after update phase")
        # the journal entry is NOT deleted here: the sum2 phase rewrites it
        # as a sum2-tagged entry (aggregate + votes) before acknowledging
        # its first vote, and the unmask phase retires it only after the
        # global model is published — the round is resumable end to end
        self.shared.flush_hook = None

    def broadcast(self) -> None:
        self.shared.events.broadcast_seed_dict(DictionaryUpdate.new(self._seed_dict))

    async def next(self):
        from .sum2 import Sum2Phase

        return Sum2Phase(self.shared, self.aggregator)

    async def handle_request(self, req: StateMachineRequest) -> None:
        if not isinstance(req, UpdateRequest):
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "not an update message")
        try:
            # off the event loop: host validation scans the full element
            # vector, and wire-ingest validation does a device transfer +
            # kernel + sync — neither may stall the loop serving the API
            # (ordering is preserved: the await completes before the
            # seed-dict insert below)
            await asyncio.get_running_loop().run_in_executor(
                None, self.aggregator.validate_aggregation, req.masked_model
            )
        except AggregationError as err:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, err.kind) from err
        store_err = await self.shared.store.coordinator.add_local_seed_dict(
            req.participant_pk, req.local_seed_dict
        )
        if store_err is not None:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, store_err.value)
        self.aggregator.stage(req.masked_model)
        if self.aggregator.pending >= self.aggregator.batch_size:
            # fold off the event loop so the API stays responsive during
            # large folds; handle_request awaits it, so folds serialize
            await asyncio.get_running_loop().run_in_executor(None, self.aggregator.flush)
            if self._ckpt is not None:
                await self._ckpt.maybe_save()
        # chaos hook (kill-matrix harness): dies BEFORE the ack leaves, so
        # with checkpoint_every_batches = 1 the journal already carries the
        # update the client will retry idempotently after restart
        maybe_kill("update")

    async def handle_partial(self, req: PartialAggregate, remaining: int) -> None:
        """Fold one edge envelope ATOMICALLY (docs/DESIGN.md §11).

        Order of checks: round identity -> per-edge watermark (idempotent
        replay ack / stale) -> count-window overshoot (atomic: the
        envelope is never split across ``count.max``) -> envelope
        self-consistency -> aggregation validation -> seed-dict
        pre-validation against a snapshot (this phase is the round's only
        seed-dict writer, so the snapshot cannot go stale under us) ->
        commit (all seed dicts, then ONE ``masked_add`` dispatch advancing
        ``nb_models`` by the member count). Every pre-commit failure
        rejects the envelope whole; a storage failure mid-commit is an
        infrastructure error that fails the round rather than leave seeds
        without models (the nb_models == seed-watermark invariant).
        """
        shared = self.shared
        if req.round_seed != shared.state.round_params.seed.as_bytes():
            EDGE_ENVELOPES.labels(outcome="rejected").inc()
            raise RequestError(
                RequestError.Kind.MESSAGE_REJECTED, "envelope from another round"
            )
        last_seq = shared.edge_watermarks.get(req.edge_id)
        if last_seq is not None and req.window_seq <= last_seq:
            if req.window_seq == last_seq:
                # the envelope AT the watermark: the edge retried after a
                # lost acknowledgement, its content is already folded —
                # ack idempotently so a successfully folded envelope is
                # not misreported as rejected data loss on the edge
                EDGE_ENVELOPES.labels(outcome="replay").inc()
                logger.info(
                    "round %d: idempotent ack for replayed edge envelope %s/%d",
                    shared.round_id,
                    req.edge_id,
                    req.window_seq,
                )
                raise EnvelopeReplay()
            EDGE_ENVELOPES.labels(outcome="stale").inc()
            raise RequestError(
                RequestError.Kind.MESSAGE_REJECTED,
                f"stale envelope: edge {req.edge_id} window {req.window_seq} "
                f"already folded (watermark {last_seq})",
            )
        if len(req) > remaining:
            raise RequestError(
                RequestError.Kind.MESSAGE_DISCARDED,
                f"envelope of {len(req)} would exceed count.max",
            )
        if len(req.members) == 0 or len(set(req.members)) != len(req.members) or sorted(
            req.seed_dicts
        ) != sorted(req.members):
            EDGE_ENVELOPES.labels(outcome="rejected").inc()
            raise RequestError(
                RequestError.Kind.MESSAGE_REJECTED, "inconsistent envelope accounting"
            )
        try:
            # off the event loop: validity scans the full element vector
            await asyncio.get_running_loop().run_in_executor(
                None, self.aggregator.validate_partial, req.masked, len(req)
            )
        except AggregationError as err:
            EDGE_ENVELOPES.labels(outcome="rejected").inc()
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, err.kind) from err
        sum_dict = await shared.store.coordinator.sum_dict() or {}
        seed_dict = await shared.store.coordinator.seed_dict() or {}
        seeded = {pk for inner in seed_dict.values() for pk in inner}
        for pk in req.members:
            local = req.seed_dicts[pk]
            if pk in seeded:
                EDGE_ENVELOPES.labels(outcome="rejected").inc()
                raise RequestError(
                    RequestError.Kind.MESSAGE_REJECTED,
                    "envelope member already seeded this round",
                )
            if len(local) != len(sum_dict) or any(spk not in sum_dict for spk in local):
                EDGE_ENVELOPES.labels(outcome="rejected").inc()
                raise RequestError(
                    RequestError.Kind.MESSAGE_REJECTED,
                    "envelope member seed dict does not match the sum dictionary",
                )
        # commit point: no rejection is possible past here
        for pk in req.members:
            store_err = await shared.store.coordinator.add_local_seed_dict(
                pk, req.seed_dicts[pk]
            )
            if store_err is not None:  # pre-validated: only infrastructure left
                raise PhaseError(
                    "EdgeEnvelope",
                    f"seed-dict commit failed mid-envelope: {store_err.value}",
                )
        await asyncio.get_running_loop().run_in_executor(
            None, self.aggregator.fold_partial, req.masked, len(req)
        )
        shared.edge_watermarks[req.edge_id] = req.window_seq
        EDGE_ENVELOPES.labels(outcome="accepted").inc()
        EDGE_MEMBERS_FOLDED.inc(len(req))
        logger.info(
            "round %d [tenant %s]: folded edge envelope %s/%d (%d members, one dispatch)",
            shared.round_id,
            shared.tenant,
            req.edge_id,
            req.window_seq,
            len(req),
        )
        if self._ckpt is not None:
            await self._ckpt.maybe_save()
        maybe_kill("update")

    async def coalesced_batch_start(self, members) -> None:
        """Batch prevalidation: when device wire ingest is on, the whole
        micro-batch's unpack + element-validity runs as ONE device dispatch
        + ONE acceptance fetch (``prevalidate_wire_batch``) instead of a
        blocking round-trip per member; ``handle_request`` then consumes
        the cached per-member verdicts in order, so validation still
        precedes each member's seed-dict insert exactly as before."""
        masked = [m.masked_model for m in members if isinstance(m, UpdateRequest)]
        if len(masked) > 1:
            await asyncio.get_running_loop().run_in_executor(
                None, self.aggregator.prevalidate_wire_batch, masked
            )

    async def coalesced_batch_done(self, n: int) -> None:
        """One stacked fold per coalesced micro-batch: the whole batch of
        staged updates is SUBMITTED to the streaming aggregation pipeline
        as a single ``masked_add`` dispatch — staging of the next batch
        overlaps the in-flight fold; the pipeline drains at phase end."""
        if self.aggregator.pending:
            await asyncio.get_running_loop().run_in_executor(None, self.aggregator.flush)
            if self._ckpt is not None:
                await self._ckpt.maybe_save()
