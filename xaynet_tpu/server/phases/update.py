"""Update phase: collect and aggregate masked model updates.

Reference behavior
(rust/xaynet-server/src/state_machine/phases/update.rs:50-184): for each
accepted ``UpdateRequest``: validate the masked object against the
aggregation state, atomically insert the participant's encrypted seed dict
(validated against the sum dictionary), then aggregate the masked model.
Afterwards the seed dictionary is fetched and broadcast for sum
participants.

TPU-native difference: accepted updates are *staged* and folded in batches
by the ``StagedAggregator`` (host numpy kernels or the sharded device fold)
instead of a per-update big-int loop; validation and seed-dict ordering are
per-update exactly as in the reference.
"""

from __future__ import annotations

import asyncio

from ...core.mask.masking import AggregationError
from ..aggregation import StagedAggregator
from ..events import DictionaryUpdate, PhaseName
from ..requests import RequestError, StateMachineRequest, UpdateRequest
from .base import PhaseError, PhaseState


class UpdatePhase(PhaseState):
    NAME = PhaseName.UPDATE

    def __init__(self, shared):
        super().__init__(shared)
        settings = shared.settings
        self.aggregator = StagedAggregator(
            config=shared.state.round_params.mask_config,
            object_size=shared.state.round_params.model_length,
            device=settings.aggregation.device,
            batch_size=settings.aggregation.batch_size,
            kernel=settings.aggregation.kernel,
            dispatch_ahead=settings.aggregation.dispatch_ahead,
            staging_buffers=settings.aggregation.staging_buffers,
        )
        self._seed_dict = None

    async def process(self) -> None:
        await self.process_requests(self.shared.settings.pet.update)
        # phase transition: drain the streaming pipeline — every submitted
        # fold completes and the deferred acceptance sync runs, off the
        # event loop (this is the one blocking synchronization point)
        await asyncio.get_running_loop().run_in_executor(None, self.aggregator.drain)
        self._seed_dict = await self.shared.store.coordinator.seed_dict()
        if not self._seed_dict:
            raise PhaseError("NoSeedDict", "seed dictionary missing after update phase")

    def broadcast(self) -> None:
        self.shared.events.broadcast_seed_dict(DictionaryUpdate.new(self._seed_dict))

    async def next(self):
        from .sum2 import Sum2Phase

        return Sum2Phase(self.shared, self.aggregator)

    async def handle_request(self, req: StateMachineRequest) -> None:
        if not isinstance(req, UpdateRequest):
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "not an update message")
        try:
            # off the event loop: host validation scans the full element
            # vector, and wire-ingest validation does a device transfer +
            # kernel + sync — neither may stall the loop serving the API
            # (ordering is preserved: the await completes before the
            # seed-dict insert below)
            await asyncio.get_running_loop().run_in_executor(
                None, self.aggregator.validate_aggregation, req.masked_model
            )
        except AggregationError as err:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, err.kind) from err
        store_err = await self.shared.store.coordinator.add_local_seed_dict(
            req.participant_pk, req.local_seed_dict
        )
        if store_err is not None:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, store_err.value)
        self.aggregator.stage(req.masked_model)
        if self.aggregator.pending >= self.aggregator.batch_size:
            # fold off the event loop so the API stays responsive during
            # large folds; handle_request awaits it, so folds serialize
            await asyncio.get_running_loop().run_in_executor(None, self.aggregator.flush)

    async def coalesced_batch_start(self, members) -> None:
        """Batch prevalidation: when device wire ingest is on, the whole
        micro-batch's unpack + element-validity runs as ONE device dispatch
        + ONE acceptance fetch (``prevalidate_wire_batch``) instead of a
        blocking round-trip per member; ``handle_request`` then consumes
        the cached per-member verdicts in order, so validation still
        precedes each member's seed-dict insert exactly as before."""
        masked = [m.masked_model for m in members if isinstance(m, UpdateRequest)]
        if len(masked) > 1:
            await asyncio.get_running_loop().run_in_executor(
                None, self.aggregator.prevalidate_wire_batch, masked
            )

    async def coalesced_batch_done(self, n: int) -> None:
        """One stacked fold per coalesced micro-batch: the whole batch of
        staged updates is SUBMITTED to the streaming aggregation pipeline
        as a single ``masked_add`` dispatch — staging of the next batch
        overlaps the in-flight fold; the pipeline drains at phase end."""
        if self.aggregator.pending:
            await asyncio.get_running_loop().run_in_executor(None, self.aggregator.flush)
