"""Journal resume dispatch: one validated entry -> the phase it re-enters.

Shared by the boot restore (``state_machine.StateMachineInitializer``) and
the in-process Failure recovery (docs/DESIGN.md §9). The entry's phase tag
decides the re-entry point:

- ``sum``     — a fresh :class:`SumPhase` with a store-offset window;
- ``update``  — :class:`UpdatePhase` with the aggregate restored;
- ``sum2``    — a fresh :class:`StagedAggregator` restored from the entry
  (shard-exact for packed device planes), then :class:`Sum2Phase` with the
  journaled votes re-seeded;
- ``unmask``  — the restored aggregator finalized straight into
  :class:`Unmask` (the publish window: the model is recomputed and
  republished idempotently; the journal retires after the publish).
"""

from __future__ import annotations

from ...resilience.checkpoint import RoundCheckpoint
from .base import PhaseState, Shared


def resume_phase(shared: Shared, ckpt: RoundCheckpoint) -> PhaseState:
    """Build the phase a VALIDATED journal entry re-enters. Raises on an
    unknown tag — callers run ``checkpoint.validate`` first, which rejects
    anything outside ``RESUMABLE_PHASES``."""
    from ..aggregation import build_staged_aggregator
    from .sum import SumPhase
    from .sum2 import Sum2Phase
    from .unmask import Unmask
    from .update import UpdatePhase

    if ckpt.phase == "sum":
        return SumPhase(shared, resume_from=ckpt)
    if ckpt.phase == "update":
        return UpdatePhase(shared, resume_from=ckpt)
    if ckpt.phase == "sum2":
        agg = build_staged_aggregator(shared)
        agg.restore_journal(ckpt)
        return Sum2Phase(shared, agg, resume_from=ckpt)
    if ckpt.phase == "unmask":
        agg = build_staged_aggregator(shared)
        agg.restore_journal(ckpt)
        return Unmask(shared, agg.finalize_inplace())
    raise ValueError(f"unresumable journal phase {ckpt.phase!r}")
