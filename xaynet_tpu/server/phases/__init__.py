"""Coordinator phase implementations (the PET round state machine).

Reference surface: rust/xaynet-server/src/state_machine/phases/.
"""

from .base import PhaseError, PhaseState, Shared
from .failure import Failure
from .idle import Idle
from .shutdown import Shutdown
from .sum import SumPhase
from .sum2 import Sum2Phase
from .unmask import Unmask
from .update import UpdatePhase

__all__ = [
    "PhaseError",
    "PhaseState",
    "Shared",
    "Failure",
    "Idle",
    "Shutdown",
    "SumPhase",
    "Sum2Phase",
    "Unmask",
    "UpdatePhase",
]
