"""Unmask phase: elect the winning mask and reveal the new global model.

Reference behavior
(rust/xaynet-server/src/state_machine/phases/unmask.rs:56-219): fetch the
two best-scored masks; the winner must be the *unique* maximum (equal top
scores are ambiguous -> round failure); validate and unmask the aggregate;
persist the global model under ``{round_id}_{hex(seed)}`` with the latest-id
pointer; publish proof to the trust anchor; broadcast the new model.

The unmask subtract runs on the vectorized limb kernels. Device rounds
arrive as a ``DeviceAggregation`` view (``aggregation.finalize_inplace``):
the subtract runs per-shard against the still-sharded accumulator — each
mesh device unmasks its own model-axis slice, the aggregate is never
gathered before subtraction, and the host ``mod_sub`` only runs when a
native fold left the accumulator host-resident. The fixed-point decode
uses the double-double fast path for f32 configs (core/mask/encode.py).
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from ...core.mask.masking import Aggregation, UnmaskingError
from ...core.mask.object import MaskObject
from ...resilience.chaos import maybe_kill
from ...telemetry import profiling
from ...telemetry.registry import get_registry
from ..events import ModelUpdate, PhaseName
from .base import PhaseError, PhaseState

logger = logging.getLogger("xaynet.coordinator")

POINTER_UPDATE_FAILURES = get_registry().counter(
    "xaynet_model_pointer_update_failures_total",
    "latest_global_model_id pointer updates abandoned after retries "
    "(the model blob IS stored; only the latest-pointer is stale).",
)


class Unmask(PhaseState):
    NAME = PhaseName.UNMASK

    def __init__(self, shared, model_agg: Aggregation):
        super().__init__(shared)
        self.model_agg = model_agg
        self.global_model: np.ndarray | None = None

    async def process(self) -> None:
        if self.shared.metrics is not None:
            n_masks = await self.shared.store.coordinator.number_of_unique_masks()
            self.shared.metrics.masks_total(self.shared.round_id, n_masks)
        best = await self.shared.store.coordinator.best_masks()
        if best is None:
            raise PhaseError("NoMask", "no masks submitted")
        mask = self._freeze_mask_dict(best)
        try:
            self.model_agg.validate_unmasking(mask)
        except UnmaskingError as err:
            raise PhaseError("Unmasking", err.kind) from err
        from ..aggregation import DeviceAggregation

        if isinstance(self.model_agg, DeviceAggregation):
            # the sharded in-place subtract records the `unmask` kernel op
            # itself (ShardedAggregator.unmask_limbs) — wrapping it again
            # here would double-count the op in /metrics
            self.global_model = self.model_agg.unmask_array(mask)
        else:
            self.global_model = profiling.timed_kernel(
                "unmask", len(self.model_agg), lambda: self.model_agg.unmask_array(mask)
            )
        await self._save_global_model()
        # chaos hook (kill-matrix harness): the publish window — the model
        # is persisted but the journal not yet retired; a restart must
        # republish idempotently (ModelStorage contract), never corrupt
        maybe_kill("unmask:publish")
        await self._publish_proof()
        # round-end page release (docs/DESIGN.md §19): the accumulator's
        # pool pages go back the moment the unmasked model is decoded and
        # persisted — this is the clean half of the leases == releases
        # round invariant (Idle's reclaim is the crash-path backstop)
        release = getattr(self.model_agg, "release_pool", None)
        if release is not None:
            release()
        if self.shared.settings.resilience.checkpoint_enabled:
            # retire the round journal: the model is published and the
            # pool pages are back — nothing left for a resume to redo
            # (Idle's delete is the backstop for disabled-journal configs)
            await self.shared.store.coordinator.delete_round_checkpoint()

    def broadcast(self) -> None:
        assert self.global_model is not None
        self.shared.events.broadcast_model(ModelUpdate.new(self.global_model))

    async def next(self):
        if self.shared.round_ctl is not None:
            # the round is complete: feed the controller's hysteresis (full
            # vs degraded is derived from the per-phase window outcomes)
            self.shared.round_ctl.round_completed()
        # tenant lifecycle (docs/DESIGN.md §23): a completed round is the
        # breaker's probe success (quarantine lift) and a drain boundary
        from ...tenancy import lifecycle as _lifecycle

        _lifecycle.note_round_completed(self.shared.tenant)
        from .idle import Idle

        return Idle(self.shared)

    # --- internals --------------------------------------------------------

    @staticmethod
    def _freeze_mask_dict(best: list[tuple[MaskObject, int]]) -> MaskObject:
        """Unique-maximum election (unmask.rs:96-115)."""
        winner, winner_count = None, 0
        for mask, count in best:
            if count > winner_count:
                winner, winner_count = mask, count
            elif count == winner_count:
                winner = None
        if winner is None:
            raise PhaseError("AmbiguousMasks", "top masks share the same score")
        return winner

    async def _save_global_model(self) -> None:
        assert self.global_model is not None
        data = np.asarray(self.global_model, dtype=np.float64).tobytes()
        model_id = await self.shared.store.models.set_global_model(
            self.shared.state.round_id,
            self.shared.state.round_params.seed.as_bytes(),
            data,
        )
        # best-effort per the reference (unmask.rs:191-198) — the retry
        # itself lives in the ResilientStore layer every storage call flows
        # through (stacking a second schedule here would retry up to
        # attempts² times against a backend the breaker already declared
        # dead). What this phase adds is the COUNT: a permanently broken
        # pointer must be visible on /metrics, not buried in a warning log.
        # The phase still completes either way (clients fall back to
        # fetching the model by explicit id).
        try:
            await self.shared.store.coordinator.set_latest_global_model_id(model_id)
        except asyncio.CancelledError:
            raise
        except Exception as err:
            POINTER_UPDATE_FAILURES.inc()
            logger.warning("failed to update latest global model id: %s", err)

    async def _publish_proof(self) -> None:
        if self.shared.store.trust_anchor is None:
            return
        assert self.global_model is not None
        data = np.asarray(self.global_model, dtype=np.float64).tobytes()
        await self.shared.store.trust_anchor.publish_proof(data)
