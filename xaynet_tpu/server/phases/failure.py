"""Failure phase: error capture and round restart.

Reference behavior
(rust/xaynet-server/src/state_machine/phases/failure.rs:30-106): a broken
request channel shuts the coordinator down; any other phase error waits for
storage readiness and restarts the round at Idle.
"""

from __future__ import annotations

import asyncio
import logging

from ..events import PhaseName
from ..requests import ChannelClosed
from .base import PhaseState

logger = logging.getLogger("xaynet.coordinator")

STORE_READY_RETRY_SECONDS = 1.0


class Failure(PhaseState):
    NAME = PhaseName.FAILURE

    def __init__(self, shared, error: Exception):
        super().__init__(shared)
        self.error = error

    async def process(self) -> None:
        logger.warning("round %d failed: %s", self.shared.round_id, self.error)
        if self.shared.metrics is not None:
            self.shared.metrics.event(self.shared.round_id, "phase_error", str(self.error))

    async def run_phase(self):
        self._announce()
        await self.process()
        if isinstance(self.error, ChannelClosed):
            from .shutdown import Shutdown

            return Shutdown(self.shared)
        await self._wait_for_store_readiness()
        from .idle import Idle

        return Idle(self.shared)

    async def _wait_for_store_readiness(self) -> None:
        while True:
            try:
                await self.shared.store.is_ready()
                return
            except Exception as err:
                logger.warning("store not ready: %s; retrying", err)
                await asyncio.sleep(STORE_READY_RETRY_SECONDS)
