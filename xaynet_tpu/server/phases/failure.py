"""Failure phase: error capture, store readiness, and round recovery.

Reference behavior
(rust/xaynet-server/src/state_machine/phases/failure.rs:30-106): a broken
request channel shuts the coordinator down; any other phase error waits for
storage readiness and restarts the round at Idle.

Resilience extensions (docs/DESIGN.md §9):

- the readiness wait uses the capped-exponential + jitter backoff policy
  instead of a fixed 1 s sleep, and the time spent waiting is metered
  (``xaynet_store_unready_seconds_total``) instead of log-only;
- when a valid mid-round checkpoint exists for the CURRENT round, the
  phase prefers **round resume** (re-entering Update with the aggregate
  restored) over a round restart — bounded by
  ``resilience.max_resume_attempts`` per round so a deterministically
  failing resume cannot loop forever.
"""

from __future__ import annotations

import asyncio
import logging

from ...resilience import checkpoint as ckpt_mod
from ...resilience.policy import RetryPolicy
from ...telemetry.registry import get_registry
from ..events import PhaseName
from ..requests import ChannelClosed
from .base import PhaseState

logger = logging.getLogger("xaynet.coordinator")

STORE_UNREADY_SECONDS = get_registry().counter(
    "xaynet_store_unready_seconds_total",
    "Seconds the Failure phase spent waiting for storage readiness.",
)
STORE_READY_CHECKS = get_registry().counter(
    "xaynet_store_ready_checks_total",
    "Failure-phase storage readiness probes, by outcome.",
    ("outcome",),
)


class Failure(PhaseState):
    NAME = PhaseName.FAILURE

    def __init__(self, shared, error: Exception, failed_phase: PhaseName | None = None):
        super().__init__(shared)
        self.error = error
        self.failed_phase = failed_phase

    async def process(self) -> None:
        logger.warning("round %d failed: %s", self.shared.round_id, self.error)
        if self.shared.metrics is not None:
            self.shared.metrics.event(self.shared.round_id, "phase_error", str(self.error))

    async def run_phase(self):
        self._announce()
        await self.process()
        if isinstance(self.error, ChannelClosed):
            from .shutdown import Shutdown

            return Shutdown(self.shared)
        await self._wait_for_store_readiness()
        resumed = await self._try_resume()
        if resumed is not None:
            return resumed
        if self.shared.round_ctl is not None:
            # only a true round RESTART feeds the controller's shrink
            # streak — a checkpoint resume keeps the round alive, and its
            # eventual completion/failure is what gets counted
            self.shared.round_ctl.round_failed()
        # tenant lifecycle (docs/DESIGN.md §23): a failed round is both a
        # breaker strike for quarantine AND a round boundary for a pending
        # drain — a resume above is neither (the round is still alive)
        from ...tenancy import lifecycle as _lifecycle

        _lifecycle.note_round_failed(self.shared.tenant)
        from .idle import Idle

        return Idle(self.shared)

    async def _wait_for_store_readiness(self) -> None:
        """Block until the store answers, backing off with jitter.

        Readiness has no give-up — the coordinator is useless without its
        store — so once the policy's ramp-up schedule is exhausted the
        probe cadence SETTLES at the cap (it must not saw-tooth back to
        the base delay and hammer a dead backend forever).
        """
        res = self.shared.settings.resilience
        policy = RetryPolicy(
            max_attempts=max(res.retry_max_attempts, 2),
            base_delay_s=max(res.retry_base_ms / 1000.0, 0.05),
            max_delay_s=max(res.retry_max_ms / 1000.0, 1.0),
            deadline_s=res.retry_deadline_s,
        )

        def delays():
            yield from policy.delays()
            while True:
                yield policy.max_delay_s

        for delay in delays():
            try:
                await self.shared.store.is_ready()
                STORE_READY_CHECKS.labels(outcome="ready").inc()
                return
            except Exception as err:
                STORE_READY_CHECKS.labels(outcome="unready").inc()
                STORE_UNREADY_SECONDS.inc(delay)
                logger.warning(
                    "store not ready: %s; retrying in %.2fs", err, delay
                )
                await asyncio.sleep(delay)

    async def _try_resume(self):
        """Re-enter the journaled phase instead of restarting the round.

        Returns the resumed phase or None. Every code path is fail-soft: a
        broken journal read/validation degrades to the Idle restart the
        pre-resilience coordinator always did.
        """
        res = self.shared.settings.resilience
        if not res.checkpoint_enabled:
            return None
        failed = self.failed_phase.value if self.failed_phase is not None else "unknown"
        attempts = self.shared.resume_attempts  # lint: tenant-ok: budget lives on this tenant's own Shared
        if attempts >= res.max_resume_attempts:
            logger.warning(
                "round %d: resume budget exhausted (%d); restarting round",
                self.shared.round_id,
                attempts,
            )
            ckpt_mod.RESUMES.labels(outcome="budget_exhausted").inc()
            ckpt_mod.RESUME_TOTAL.labels(phase=failed, outcome="budget_exhausted").inc()
            return None
        ckpt = await ckpt_mod.load(self.shared.store)
        if ckpt is None:
            return None
        if self.failed_phase is not None and self.failed_phase.value != ckpt.phase:
            # a journal entry for another phase cannot help THIS failure:
            # participants of the failed phase would never resend into a
            # re-entered earlier phase, so the resume just times out (e.g.
            # sum2 failed before its base entry landed, journal still says
            # "update") — restart the round instead of burning budget
            logger.warning(
                "round %d: journal phase %r != failed phase %r; restarting round",
                self.shared.round_id,
                ckpt.phase,
                failed,
            )
            ckpt_mod.RESUMES.labels(outcome="invalid").inc()
            ckpt_mod.RESUME_TOTAL.labels(phase=ckpt.phase, outcome="invalid").inc()
            return None
        try:
            reason = await ckpt_mod.validate(ckpt, self.shared.state, self.shared.store)
        except Exception as err:
            reason = f"validation failed: {err}"
        if reason is not None:
            logger.warning(  # lint: taint-ok: validation reason carries counts/names only, never key bytes
                "round %d: journal entry not resumable (%s); restarting round",
                self.shared.round_id,
                reason,
            )
            ckpt_mod.RESUMES.labels(outcome="invalid").inc()
            ckpt_mod.RESUME_TOTAL.labels(phase=ckpt.phase, outcome="invalid").inc()
            return None
        self.shared.resume_attempts = attempts + 1  # lint: tenant-ok: budget lives on this tenant's own Shared
        ckpt_mod.RESUMES.labels(outcome="resumed").inc()
        ckpt_mod.RESUME_TOTAL.labels(phase=ckpt.phase, outcome="resumed").inc()
        logger.info(
            "round %d: resuming %s phase from journal (%d models, attempt %d/%d)",
            self.shared.round_id,
            ckpt.phase,
            ckpt.nb_models,
            attempts + 1,
            res.max_resume_attempts,
        )
        from .resume import resume_phase

        return resume_phase(self.shared, ckpt)
