"""Phase machinery: shared context, run loop, count/time request windows.

Functional port of the reference's phase framework (reference:
rust/xaynet-server/src/state_machine/phases/phase.rs:49-231 and
handler.rs:96-202):

- ``run_phase``: broadcast the phase event -> ``process`` -> purge requests
  left over from the phase -> ``broadcast`` -> ``next``; any error routes to
  the Failure phase.
- request windows: accept up to ``count.max`` requests during
  ``[0, time.min]``; then keep accepting until ``count.min`` is reached,
  bounded by ``time.max`` — too few accepted requests is a
  ``PhaseTimeout``. Requests beyond ``count.max`` are *discarded*; requests
  that fail protocol checks are *rejected*.

Liveness extension (docs/DESIGN.md §10): a phase may carry a
``count.quorum`` (quorum <= min <= max). Once ``time.min`` has elapsed and
arrivals stall — no accepted message for ``liveness.stall_grace_s`` — a
phase with ``accepted >= quorum`` closes successfully in DEGRADED mode
instead of waiting out ``time.max`` for a ``count.min`` that churned-out
participants will never deliver; the same fallback applies when
``time.max`` expires at/above quorum. Every window completion is counted
on ``xaynet_phase_outcome_total{phase,outcome=full|degraded|timeout}``
and reported to the round controller when one is installed.
"""

from __future__ import annotations

import asyncio
import logging
import time as time_mod
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from ...storage.traits import Store
from ...telemetry import tracing as trace
from ...telemetry.recorder import flight_dump
from ...telemetry.registry import get_registry
from ...utils import tracing
from ..events import EventPublisher, PhaseName
from ..requests import (
    ChannelClosed,
    CoalescedUpdates,
    EnvelopeReplay,
    PartialAggregate,
    RequestError,
    RequestReceiver,
    StateMachineRequest,
)
from ..settings import PhaseSettings, Settings, Sum2Settings

if TYPE_CHECKING:
    from ..coordinator import CoordinatorState

logger = logging.getLogger("xaynet.coordinator")

PHASE_OUTCOMES = get_registry().counter(
    "xaynet_phase_outcome_total",
    "Request-window phase completions, by phase and outcome "
    "(full | degraded | timeout).",
    ("phase", "outcome"),
)

# one span name per phase — spelled out (not built in a loop) so the
# analysis `span` pass can cross-check the literal set against the DESIGN
# §16 span table exactly like the metrics table
_PHASE_SPANS: dict[str, str] = {
    "idle": trace.declare_span("phase.idle"),
    "sum": trace.declare_span("phase.sum"),
    "update": trace.declare_span("phase.update"),
    "sum2": trace.declare_span("phase.sum2"),
    "unmask": trace.declare_span("phase.unmask"),
    "failure": trace.declare_span("phase.failure"),
    "shutdown": trace.declare_span("phase.shutdown"),
}
SPAN_PARTIAL = trace.declare_span("edge.upstream_fold")


class PhaseError(Exception):
    """A phase failed; drives the transition into Failure."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}{': ' + detail if detail else ''}")
        self.kind = kind


class PhaseTimeout(PhaseError):
    """The window closed below quorum; carries the full window diagnostics
    (who arrived, what the thresholds were, how long the phase ran) so the
    Failure-phase log line and the phase_error metric event name the gap
    instead of a static string."""

    def __init__(
        self,
        accepted: Optional[int] = None,
        count_min: int = 0,
        quorum: int = 0,
        rejected: int = 0,
        discarded: int = 0,
        seconds: float = 0.0,
    ):
        detail = "not enough messages received within the time window"
        if accepted is not None:
            detail += (
                f" ({accepted} accepted / min {count_min} / quorum {quorum}; "
                f"{rejected} rejected, {discarded} discarded; "
                f"{seconds:.1f}s in phase)"
            )
        super().__init__("PhaseTimeout", detail)
        self.accepted = accepted
        self.count_min = count_min
        self.quorum = quorum
        self.rejected = rejected
        self.discarded = discarded
        self.seconds = seconds


@dataclass
class Shared:
    """Context threaded through all phases (single-writer)."""

    state: "CoordinatorState"
    request_rx: RequestReceiver
    events: EventPublisher
    store: Store
    settings: Settings
    metrics: Optional[object] = None
    # the tenant this round state belongs to (docs/DESIGN.md §19): keys the
    # aggregator's pool leases and scheduler slots, labels phase spans,
    # flight dumps and tenant metric families, scopes checkpoints/storage
    tenant: str = "default"
    # Failure-phase round-resume budget for the CURRENT round (reset by
    # Idle); bounds how often one round may re-enter Update from its
    # checkpoint before falling back to a restart
    resume_attempts: int = 0
    # adaptive count-window controller ([liveness] adaptive = true); phases
    # report window outcomes here, Unmask/Failure report round outcomes
    round_ctl: Optional[object] = None
    # per-edge partial-aggregate watermarks for the CURRENT round (reset by
    # Idle): edge_id -> highest window_seq folded. A redelivered envelope
    # (edge retry after a lost acknowledgement) is rejected as stale
    # instead of folded twice (docs/DESIGN.md §11).
    edge_watermarks: dict = field(default_factory=dict)
    # graceful-shutdown flush (docs/DESIGN.md §9): the phase whose journal
    # cadence can lag live state (Update) installs its ``save_now`` here so
    # the runner's SIGTERM/SIGINT path can persist a final journal entry
    # before exiting; per-event-journaling phases leave it None
    flush_hook: Optional[object] = None

    def set_round_id(self, round_id: int) -> None:
        self.state.round_id = round_id
        self.events.set_round_id(round_id)

    @property
    def round_id(self) -> int:
        return self.state.round_id


def reduce_count_window(params, offset: int):
    """Shrink a phase's count window by ``offset`` already-journaled
    arrivals (a resumed phase re-opens for the REMAINDER only; the restored
    participants will not resend). A fully-satisfied window drains straight
    through: min/max/quorum clamp at 0."""
    import dataclasses

    if not offset:
        return params
    count = dataclasses.replace(
        params.count,
        min=max(params.count.min - offset, 0),
        max=max(params.count.max - offset, 0),
        quorum=(
            None
            if params.count.quorum is None
            else max(params.count.quorum - offset, 0)
        ),
    )
    return dataclasses.replace(params, count=count)


class _Counter:
    """Accepted/rejected/discarded bookkeeping (handler.rs:28-89), plus the
    liveness quorum (quorum == min when no degraded completion is armed)."""

    def __init__(self, count_min: int, count_max: int, quorum: Optional[int] = None):
        self.min = count_min
        self.max = count_max
        self.quorum = count_min if quorum is None else min(quorum, count_min)
        self.accepted = 0
        self.rejected = 0
        self.discarded = 0

    @property
    def has_enough(self) -> bool:
        return self.accepted >= self.min

    @property
    def has_quorum(self) -> bool:
        return self.accepted >= self.quorum

    @property
    def has_overmuch(self) -> bool:
        return self.accepted >= self.max


class PhaseState:
    """Base class for phases; subclasses set NAME and implement hooks."""

    NAME: PhaseName
    # arrivals the round controller should count ON TOP of this window's
    # accepted requests (a checkpoint-resumed update phase runs a reduced
    # window: the restored models were real arrivals, and omitting them
    # would make a resumed 100-participant round look like a 5-participant
    # deployment to the adaptive shrink clamp)
    arrivals_offset: int = 0

    def __init__(self, shared: Shared):
        self.shared = shared

    # --- hooks ------------------------------------------------------------

    async def process(self) -> None:
        raise NotImplementedError

    def broadcast(self) -> None:
        pass

    async def next(self) -> Optional["PhaseState"]:
        raise NotImplementedError

    async def handle_request(self, req: StateMachineRequest) -> None:
        """Phase-specific request handling; raises ``RequestError`` to reject."""
        raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "phase accepts no requests")

    async def handle_partial(self, req: PartialAggregate, remaining: int) -> None:
        """Phase-specific partial-aggregate handling (edge tier); raises
        ``RequestError`` to reject the WHOLE envelope — partials are atomic
        and only the update phase accepts them. ``remaining`` is the count
        window's free capacity: the overshoot check lives in the handler,
        AFTER the watermark replay check, so a redelivered already-folded
        envelope is still acked idempotently at a nearly-closed window."""
        raise RequestError(
            RequestError.Kind.MESSAGE_REJECTED, "phase accepts no partial aggregates"
        )

    async def coalesced_batch_start(self, members) -> None:
        """Hook: a coalesced micro-batch is about to be processed
        member-wise (the update phase batch-prevalidates device wire
        updates here — one device round-trip for the whole group)."""

    async def coalesced_batch_done(self, n: int) -> None:
        """Hook: a coalesced micro-batch of ``n`` members was just processed
        (the update phase flushes its staged fold here)."""

    # --- run loop ---------------------------------------------------------

    def _announce(self) -> None:
        """Broadcast + record the phase entry (every phase, every override)."""
        self.shared.events.broadcast_phase(self.NAME)
        if self.shared.metrics is not None:
            self.shared.metrics.phase(self.shared.round_id, self.NAME.value)
        logger.info("round %d: entering %s phase", self.shared.round_id, self.NAME.value)

    async def run_phase(self) -> Optional["PhaseState"]:
        self._announce()
        t0 = time_mod.monotonic()
        # the phase span brackets exactly what phase_duration measures
        # (process + purge), so tools/trace_report.py can cross-check the
        # trace against the round report's phase walls. Idle straddles the
        # round boundary (it COMPUTES the seed the new round's trace id
        # derives from), so its span is a fresh root — parenting it to the
        # previous round's root would leave an orphan in the new round's
        # export.
        idle_ctx = (
            trace.TraceContext(trace.new_id()) if self.NAME is PhaseName.IDLE else None
        )
        with trace.get_tracer().span(
            _PHASE_SPANS[self.NAME.value],
            ctx=idle_ctx,
            round_id=self.shared.round_id,
            tenant=self.shared.tenant,
        ) as phase_span:
            # the window outcome lands on the phase span too
            # (_record_window_outcome), so the timeline fold can tell a
            # degraded round from the span buffer alone
            self._phase_span = phase_span
            try:
                await self.process()
                await self.purge_outdated_requests()
            except (PhaseError, ChannelClosed) as err:
                self._record_duration(t0)
                return await self._into_failure(err)
            except Exception as err:  # storage or internal errors
                self._record_duration(t0)
                return await self._into_failure(PhaseError(type(err).__name__, str(err)))
        self._record_duration(t0)
        self.broadcast()
        return await self.next()

    def _record_duration(self, t0: float) -> None:
        if self.shared.metrics is not None and hasattr(self.shared.metrics, "phase_duration"):
            self.shared.metrics.phase_duration(
                self.shared.round_id, self.NAME.value, time_mod.monotonic() - t0
            )

    async def _into_failure(self, err: Exception) -> "PhaseState":
        from .failure import Failure

        logger.warning("round %d: %s phase failed: %s", self.shared.round_id, self.NAME.value, err)
        return Failure(self.shared, err, failed_phase=self.NAME)

    async def purge_outdated_requests(self) -> None:
        """Reject every request still queued from this phase (phase.rs:183-192).

        Purges are counted separately from in-window rejects (``purged``
        outcome): a degraded close rejects every straggler still queued, and
        that burst must not pollute reject-rate dashboards."""
        while True:
            env = self.shared.request_rx.try_recv()
            if env is None:
                return
            self._respond(env, RequestError(RequestError.Kind.MESSAGE_REJECTED, "phase ended"))
            metrics = self.shared.metrics
            if metrics is not None:
                if hasattr(metrics, "message_purged"):
                    metrics.message_purged(self.shared.round_id, self.NAME.value)
                else:  # pre-purge recorders (test spies): keep the old bucket
                    metrics.message_rejected(self.shared.round_id, self.NAME.value)

    # --- request windows --------------------------------------------------

    async def process_requests(self, params: PhaseSettings | Sum2Settings) -> str:
        """Run the count/time request window; returns the outcome
        (``"full"`` or ``"degraded"``) or raises :class:`PhaseTimeout`."""
        # effective_quorum re-clamps quorum <= min after any adaptive
        # controller adjustment to min (settings.CountSettings)
        counter = _Counter(
            params.count.min,
            params.count.max,
            getattr(params.count, "effective_quorum", None),
        )
        logger.debug(
            "processing requests for min %.1fs / max %.1fs (count %d..%d, quorum %d)",
            params.time.min,
            params.time.max,
            params.count.min,
            params.count.max,
            counter.quorum,
        )
        t0 = time_mod.monotonic()
        await self._process_during(params.time.min, counter)
        time_left = max(params.time.max - params.time.min, 0.0)
        try:
            await self._process_until_enough(counter, time_mod.monotonic() + time_left)
        except asyncio.TimeoutError:
            # only raised below quorum: at/above quorum the deadline closes
            # the window degraded by RETURNING between requests (never by
            # cancelling one mid-flight — see _process_until_enough)
            self._record_window_outcome(counter, "timeout", t0)
            raise PhaseTimeout(
                accepted=counter.accepted,
                count_min=counter.min,
                quorum=counter.quorum,
                rejected=counter.rejected,
                discarded=counter.discarded,
                seconds=time_mod.monotonic() - t0,
            ) from None
        outcome = "full" if counter.has_enough else "degraded"
        self._record_window_outcome(counter, outcome, t0)
        logger.log(
            logging.WARNING if outcome == "degraded" else logging.INFO,
            "round %d %s: %s close — %d accepted (min %d, quorum %d, max %d), "
            "%d rejected, %d discarded",
            self.shared.round_id,
            self.NAME.value,
            outcome,
            counter.accepted,
            counter.min,
            counter.quorum,
            counter.max,
            counter.rejected,
            counter.discarded,
        )
        return outcome

    def _record_window_outcome(self, counter: _Counter, outcome: str, t0: float) -> None:
        PHASE_OUTCOMES.labels(phase=self.NAME.value, outcome=outcome).inc()
        phase_span = getattr(self, "_phase_span", None)
        if phase_span is not None:
            phase_span.set(outcome=outcome)
        if outcome in ("degraded", "timeout"):
            # forensic bundle: the span ring holds what led up to the
            # degraded close / below-quorum timeout (recent request, ingest
            # and fold spans), the deltas show which counters moved
            flight_dump(
                "degraded-close" if outcome == "degraded" else "phase-timeout",
                f"round {self.shared.round_id} {self.NAME.value}: "
                f"{counter.accepted} accepted (min {counter.min}, quorum "
                f"{counter.quorum}), {counter.rejected} rejected, "
                f"{counter.discarded} discarded",
                phase=self.NAME.value,
                round_id=self.shared.round_id,
                tenant=self.shared.tenant,
            )
        if self.shared.round_ctl is not None:
            self.shared.round_ctl.observe_phase(
                self.NAME.value,
                counter.accepted + self.arrivals_offset,
                outcome,
                time_mod.monotonic() - t0,
            )

    async def _process_during(self, duration: float, counter: _Counter) -> None:
        deadline = time_mod.monotonic() + duration
        while True:
            remaining = deadline - time_mod.monotonic()
            if remaining <= 0:
                return
            try:
                env = await asyncio.wait_for(self.shared.request_rx.next_request(), remaining)
            except asyncio.TimeoutError:
                return
            await self._process_single(env, counter)

    async def _process_until_enough(self, counter: _Counter, deadline: float) -> None:
        """Accept until ``count.min`` — or until the ``time.max`` deadline
        or, with a quorum armed, until arrivals STALL at/above quorum: no
        accepted message for ``liveness.stall_grace_s`` closes the window
        degraded (returning normally; the caller decides full vs degraded
        from the counter). A rejected/discarded straggler does not reset
        the stall clock — only acceptances prove the phase is still making
        progress.

        The window boundary (deadline or stall) is only ever declared
        BETWEEN requests: a request being handled always runs to
        completion first, so a degraded close can never strand a
        half-applied update (a seed-dict entry whose model was never
        staged would break the nb_models == seed-watermark unmask
        invariant). Below quorum the deadline raises ``TimeoutError``
        between requests instead — the caller turns it into the diagnostic
        :class:`PhaseTimeout`."""
        quorum_armed = counter.quorum < counter.min
        stall_grace = self.shared.settings.liveness.stall_grace_s
        last_accept = time_mod.monotonic()
        while not counter.has_enough:
            now = time_mod.monotonic()
            time_left = deadline - now
            at_quorum = quorum_armed and counter.has_quorum
            if time_left <= 0 or (at_quorum and now - last_accept >= stall_grace):
                # the window is closing — but a request that arrived IN
                # time may still sit queued behind slow processing (it
                # might even lift the phase to quorum or min); declaring
                # the close without draining it would purge it
                env = self.shared.request_rx.try_recv()
                if env is None:
                    if at_quorum:
                        return  # degraded close (caller reads the counter)
                    raise asyncio.TimeoutError  # time.max expired below quorum
            else:
                wait = time_left
                if at_quorum:
                    wait = min(wait, stall_grace - (now - last_accept))
                try:
                    env = await asyncio.wait_for(
                        self.shared.request_rx.next_request(), wait
                    )
                except asyncio.TimeoutError:
                    continue  # re-evaluate the deadline / stall clock
            accepted_before = counter.accepted
            await self._process_single(env, counter)
            if counter.accepted > accepted_before:
                last_accept = time_mod.monotonic()

    async def _process_single(self, env, counter: _Counter) -> None:
        if isinstance(env.request, CoalescedUpdates):
            # unpack the micro-batch: every member is counted, handled and
            # answered exactly as if it had arrived alone (count.min/max
            # protocol semantics are per UPDATE, not per envelope), then the
            # phase gets one batch-done hook for the stacked fold dispatch
            try:
                await self.coalesced_batch_start(env.request.members)
                for member_env in env.request.envelopes(env.request_id):
                    await self._process_single(member_env, counter)
                await self.coalesced_batch_done(len(env.request))
            except BaseException as err:
                # infrastructure failure OR cancellation (phase window
                # expiring) mid-batch: EVERY future must still resolve — a
                # dangling member would wedge the coalescer (and its shard
                # worker) for the life of the process
                failure = (
                    err
                    if isinstance(err, RequestError)
                    else RequestError(
                        RequestError.Kind.INTERNAL, str(err) or type(err).__name__
                    )
                )
                self._respond(env, failure)  # fans out to pending members
                raise
            self._respond(env, None)
            return
        if isinstance(env.request, PartialAggregate):
            await self._process_partial(env, counter)
            return
        if counter.has_overmuch:
            counter.discarded += 1
            if self.shared.metrics is not None:
                self.shared.metrics.message_discarded(self.shared.round_id, self.NAME.value)
            self._respond(env, RequestError(RequestError.Kind.MESSAGE_DISCARDED))
            return
        t0 = time_mod.monotonic()
        try:
            with tracing.use_request_id(env.request_id), tracing.span(
                "handle_request", phase=self.NAME.value
            ):
                await self.handle_request(env.request)
        except RequestError as err:
            counter.rejected += 1
            self._record_handled(t0)
            if self.shared.metrics is not None:
                self.shared.metrics.message_rejected(self.shared.round_id, self.NAME.value)
            self._respond(env, err)
            return
        except BaseException as err:
            # infrastructure failure (e.g. storage outage) or cancellation
            # (phase window expiring mid-handle): resolve the requester's
            # future before the phase error propagates, or the client would
            # wait forever on a round that already failed
            self._respond(
                env,
                RequestError(RequestError.Kind.INTERNAL, str(err) or type(err).__name__),
            )
            raise
        counter.accepted += 1
        self._record_handled(t0)
        if self.shared.metrics is not None:
            self.shared.metrics.message_accepted(self.shared.round_id, self.NAME.value)
        self._respond(env, None)

    async def _process_partial(self, env, counter: _Counter) -> None:
        """One edge envelope, accepted WHOLE or rejected WHOLE.

        The window accounting treats the envelope as its member count
        (count.min/max/quorum are per UPDATE, not per envelope): an
        envelope that would overshoot ``count.max`` is discarded atomically
        — never split across the boundary — and an accepted one advances
        the counter (and the stall clock) by every member it carried. The
        overshoot check itself lives in the handler so the watermark can
        ack a replayed envelope idempotently even at a nearly-closed
        window (its members already count).
        """
        k = len(env.request)
        t0 = time_mod.monotonic()
        try:
            with tracing.use_request_id(env.request_id), tracing.span(
                "handle_partial", phase=self.NAME.value
            ), trace.get_tracer().span(
                SPAN_PARTIAL,
                link=trace.parse_header(getattr(env.request, "trace", None)),
                edge_id=getattr(env.request, "edge_id", ""),
                members=k,
            ):
                await self.handle_partial(
                    env.request, counter.max - counter.accepted
                )
        except EnvelopeReplay:
            # already folded (the edge retried after a lost ack): success,
            # but the window counter must NOT advance a second time
            self._record_handled(t0)
            self._respond(env, None)
            return
        except RequestError as err:
            self._record_handled(t0)
            if err.kind is RequestError.Kind.MESSAGE_DISCARDED:
                counter.discarded += 1
                if self.shared.metrics is not None:
                    self.shared.metrics.message_discarded(
                        self.shared.round_id, self.NAME.value
                    )
            else:
                counter.rejected += 1
                if self.shared.metrics is not None:
                    self.shared.metrics.message_rejected(
                        self.shared.round_id, self.NAME.value
                    )
            self._respond(env, err)
            return
        except BaseException as err:
            self._respond(
                env,
                RequestError(RequestError.Kind.INTERNAL, str(err) or type(err).__name__),
            )
            raise
        counter.accepted += k
        self._record_handled(t0)
        if self.shared.metrics is not None:
            for _ in range(k):  # dashboards count UPDATES, not envelopes
                self.shared.metrics.message_accepted(self.shared.round_id, self.NAME.value)
        self._respond(env, None)

    def _record_handled(self, t0: float) -> None:
        """Per-request handler latency; registry-only (the bridge implements
        it, line-protocol sinks and test stubs need not)."""
        metrics = self.shared.metrics
        if metrics is not None and hasattr(metrics, "request_handled"):
            metrics.request_handled(
                self.shared.round_id, self.NAME.value, time_mod.monotonic() - t0
            )

    @staticmethod
    def _respond(env, error: Optional[Exception]) -> None:
        if error is not None and isinstance(env.request, CoalescedUpdates):
            # purge / infrastructure failure on a whole micro-batch: members
            # the phase never reached inherit the envelope's verdict
            env.request.reject_members(error)
        if env.response.done():
            return
        if error is None:
            env.response.set_result(None)
        else:
            env.response.set_exception(error)
