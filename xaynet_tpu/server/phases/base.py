"""Phase machinery: shared context, run loop, count/time request windows.

Functional port of the reference's phase framework (reference:
rust/xaynet-server/src/state_machine/phases/phase.rs:49-231 and
handler.rs:96-202):

- ``run_phase``: broadcast the phase event -> ``process`` -> purge requests
  left over from the phase -> ``broadcast`` -> ``next``; any error routes to
  the Failure phase.
- request windows: accept up to ``count.max`` requests during
  ``[0, time.min]``; then keep accepting until ``count.min`` is reached,
  bounded by ``time.max`` — too few accepted requests is a
  ``PhaseTimeout``. Requests beyond ``count.max`` are *discarded*; requests
  that fail protocol checks are *rejected*.
"""

from __future__ import annotations

import asyncio
import logging
import time as time_mod
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from ...storage.traits import Store
from ...utils import tracing
from ..events import EventPublisher, PhaseName
from ..requests import (
    ChannelClosed,
    CoalescedUpdates,
    RequestError,
    RequestReceiver,
    StateMachineRequest,
)
from ..settings import PhaseSettings, Settings, Sum2Settings

if TYPE_CHECKING:
    from ..coordinator import CoordinatorState

logger = logging.getLogger("xaynet.coordinator")


class PhaseError(Exception):
    """A phase failed; drives the transition into Failure."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}{': ' + detail if detail else ''}")
        self.kind = kind


class PhaseTimeout(PhaseError):
    def __init__(self):
        super().__init__("PhaseTimeout", "not enough messages received within the time window")


@dataclass
class Shared:
    """Context threaded through all phases (single-writer)."""

    state: "CoordinatorState"
    request_rx: RequestReceiver
    events: EventPublisher
    store: Store
    settings: Settings
    metrics: Optional[object] = None
    # Failure-phase round-resume budget for the CURRENT round (reset by
    # Idle); bounds how often one round may re-enter Update from its
    # checkpoint before falling back to a restart
    resume_attempts: int = 0

    def set_round_id(self, round_id: int) -> None:
        self.state.round_id = round_id
        self.events.set_round_id(round_id)

    @property
    def round_id(self) -> int:
        return self.state.round_id


class _Counter:
    """Accepted/rejected/discarded bookkeeping (handler.rs:28-89)."""

    def __init__(self, count_min: int, count_max: int):
        self.min = count_min
        self.max = count_max
        self.accepted = 0
        self.rejected = 0
        self.discarded = 0

    @property
    def has_enough(self) -> bool:
        return self.accepted >= self.min

    @property
    def has_overmuch(self) -> bool:
        return self.accepted >= self.max


class PhaseState:
    """Base class for phases; subclasses set NAME and implement hooks."""

    NAME: PhaseName

    def __init__(self, shared: Shared):
        self.shared = shared

    # --- hooks ------------------------------------------------------------

    async def process(self) -> None:
        raise NotImplementedError

    def broadcast(self) -> None:
        pass

    async def next(self) -> Optional["PhaseState"]:
        raise NotImplementedError

    async def handle_request(self, req: StateMachineRequest) -> None:
        """Phase-specific request handling; raises ``RequestError`` to reject."""
        raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "phase accepts no requests")

    async def coalesced_batch_start(self, members) -> None:
        """Hook: a coalesced micro-batch is about to be processed
        member-wise (the update phase batch-prevalidates device wire
        updates here — one device round-trip for the whole group)."""

    async def coalesced_batch_done(self, n: int) -> None:
        """Hook: a coalesced micro-batch of ``n`` members was just processed
        (the update phase flushes its staged fold here)."""

    # --- run loop ---------------------------------------------------------

    def _announce(self) -> None:
        """Broadcast + record the phase entry (every phase, every override)."""
        self.shared.events.broadcast_phase(self.NAME)
        if self.shared.metrics is not None:
            self.shared.metrics.phase(self.shared.round_id, self.NAME.value)
        logger.info("round %d: entering %s phase", self.shared.round_id, self.NAME.value)

    async def run_phase(self) -> Optional["PhaseState"]:
        self._announce()
        t0 = time_mod.monotonic()
        try:
            await self.process()
            await self.purge_outdated_requests()
        except (PhaseError, ChannelClosed) as err:
            self._record_duration(t0)
            return await self._into_failure(err)
        except Exception as err:  # storage or internal errors
            self._record_duration(t0)
            return await self._into_failure(PhaseError(type(err).__name__, str(err)))
        self._record_duration(t0)
        self.broadcast()
        return await self.next()

    def _record_duration(self, t0: float) -> None:
        if self.shared.metrics is not None and hasattr(self.shared.metrics, "phase_duration"):
            self.shared.metrics.phase_duration(
                self.shared.round_id, self.NAME.value, time_mod.monotonic() - t0
            )

    async def _into_failure(self, err: Exception) -> "PhaseState":
        from .failure import Failure

        logger.warning("round %d: %s phase failed: %s", self.shared.round_id, self.NAME.value, err)
        return Failure(self.shared, err, failed_phase=self.NAME)

    async def purge_outdated_requests(self) -> None:
        """Reject every request still queued from this phase (phase.rs:183-192)."""
        while True:
            env = self.shared.request_rx.try_recv()
            if env is None:
                return
            self._respond(env, RequestError(RequestError.Kind.MESSAGE_REJECTED, "phase ended"))
            if self.shared.metrics is not None:
                self.shared.metrics.message_rejected(self.shared.round_id, self.NAME.value)

    # --- request windows --------------------------------------------------

    async def process_requests(self, params: PhaseSettings | Sum2Settings) -> None:
        counter = _Counter(params.count.min, params.count.max)
        logger.debug(
            "processing requests for min %.1fs / max %.1fs (count %d..%d)",
            params.time.min,
            params.time.max,
            params.count.min,
            params.count.max,
        )
        await self._process_during(params.time.min, counter)
        time_left = max(params.time.max - params.time.min, 0.0)
        try:
            await asyncio.wait_for(self._process_until_enough(counter), timeout=time_left)
        except asyncio.TimeoutError:
            raise PhaseTimeout() from None
        logger.info(
            "round %d %s: %d accepted (min %d, max %d), %d rejected, %d discarded",
            self.shared.round_id,
            self.NAME.value,
            counter.accepted,
            counter.min,
            counter.max,
            counter.rejected,
            counter.discarded,
        )

    async def _process_during(self, duration: float, counter: _Counter) -> None:
        deadline = time_mod.monotonic() + duration
        while True:
            remaining = deadline - time_mod.monotonic()
            if remaining <= 0:
                return
            try:
                env = await asyncio.wait_for(self.shared.request_rx.next_request(), remaining)
            except asyncio.TimeoutError:
                return
            await self._process_single(env, counter)

    async def _process_until_enough(self, counter: _Counter) -> None:
        while not counter.has_enough:
            env = await self.shared.request_rx.next_request()
            await self._process_single(env, counter)

    async def _process_single(self, env, counter: _Counter) -> None:
        if isinstance(env.request, CoalescedUpdates):
            # unpack the micro-batch: every member is counted, handled and
            # answered exactly as if it had arrived alone (count.min/max
            # protocol semantics are per UPDATE, not per envelope), then the
            # phase gets one batch-done hook for the stacked fold dispatch
            try:
                await self.coalesced_batch_start(env.request.members)
                for member_env in env.request.envelopes(env.request_id):
                    await self._process_single(member_env, counter)
                await self.coalesced_batch_done(len(env.request))
            except BaseException as err:
                # infrastructure failure OR cancellation (phase window
                # expiring) mid-batch: EVERY future must still resolve — a
                # dangling member would wedge the coalescer (and its shard
                # worker) for the life of the process
                failure = (
                    err
                    if isinstance(err, RequestError)
                    else RequestError(
                        RequestError.Kind.INTERNAL, str(err) or type(err).__name__
                    )
                )
                self._respond(env, failure)  # fans out to pending members
                raise
            self._respond(env, None)
            return
        if counter.has_overmuch:
            counter.discarded += 1
            if self.shared.metrics is not None:
                self.shared.metrics.message_discarded(self.shared.round_id, self.NAME.value)
            self._respond(env, RequestError(RequestError.Kind.MESSAGE_DISCARDED))
            return
        t0 = time_mod.monotonic()
        try:
            with tracing.use_request_id(env.request_id), tracing.span(
                "handle_request", phase=self.NAME.value
            ):
                await self.handle_request(env.request)
        except RequestError as err:
            counter.rejected += 1
            self._record_handled(t0)
            if self.shared.metrics is not None:
                self.shared.metrics.message_rejected(self.shared.round_id, self.NAME.value)
            self._respond(env, err)
            return
        except BaseException as err:
            # infrastructure failure (e.g. storage outage) or cancellation
            # (phase window expiring mid-handle): resolve the requester's
            # future before the phase error propagates, or the client would
            # wait forever on a round that already failed
            self._respond(
                env,
                RequestError(RequestError.Kind.INTERNAL, str(err) or type(err).__name__),
            )
            raise
        counter.accepted += 1
        self._record_handled(t0)
        if self.shared.metrics is not None:
            self.shared.metrics.message_accepted(self.shared.round_id, self.NAME.value)
        self._respond(env, None)

    def _record_handled(self, t0: float) -> None:
        """Per-request handler latency; registry-only (the bridge implements
        it, line-protocol sinks and test stubs need not)."""
        metrics = self.shared.metrics
        if metrics is not None and hasattr(metrics, "request_handled"):
            metrics.request_handled(
                self.shared.round_id, self.NAME.value, time_mod.monotonic() - t0
            )

    @staticmethod
    def _respond(env, error: Optional[Exception]) -> None:
        if error is not None and isinstance(env.request, CoalescedUpdates):
            # purge / infrastructure failure on a whole micro-batch: members
            # the phase never reached inherit the envelope's verdict
            env.request.reject_members(error)
        if env.response.done():
            return
        if error is None:
            env.response.set_result(None)
        else:
            env.response.set_exception(error)
