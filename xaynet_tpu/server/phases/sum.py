"""Sum phase: collect ephemeral keys from sum participants.

Reference behavior (rust/xaynet-server/src/state_machine/phases/sum.rs:43-126):
accept ``SumRequest``s within the count/time window, adding each
(participant pk -> ephemeral pk) entry to the sum dictionary; duplicates are
rejected. On success the sum dictionary is fetched and broadcast for update
participants.
"""

from __future__ import annotations

from ..events import DictionaryUpdate, PhaseName
from ..requests import RequestError, StateMachineRequest, SumRequest
from .base import PhaseError, PhaseState


class SumPhase(PhaseState):
    NAME = PhaseName.SUM

    def __init__(self, shared):
        super().__init__(shared)
        self._sum_dict = None

    async def process(self) -> None:
        await self.process_requests(self.shared.settings.pet.sum)
        self._sum_dict = await self.shared.store.coordinator.sum_dict()
        if not self._sum_dict:
            raise PhaseError("NoSumDict", "sum dictionary missing after sum phase")

    def broadcast(self) -> None:
        self.shared.events.broadcast_sum_dict(DictionaryUpdate.new(self._sum_dict))

    async def next(self):
        from .update import UpdatePhase

        return UpdatePhase(self.shared)

    async def handle_request(self, req: StateMachineRequest) -> None:
        if not isinstance(req, SumRequest):
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "not a sum message")
        err = await self.shared.store.coordinator.add_sum_participant(
            req.participant_pk, req.ephm_pk
        )
        if err is not None:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, err.value)
