"""Sum phase: collect ephemeral keys from sum participants.

Reference behavior (rust/xaynet-server/src/state_machine/phases/sum.rs:43-126):
accept ``SumRequest``s within the count/time window, adding each
(participant pk -> ephemeral pk) entry to the sum dictionary; duplicates are
rejected. On success the sum dictionary is fetched and broadcast for update
participants.

Resilience (docs/DESIGN.md §9): with ``[resilience] checkpoint_enabled``
every ACCEPTED sum participant is journaled before the acknowledgement
leaves — a crash mid-sum resumes into a reduced window covering only the
participants still missing; the store-held dictionary (replayed from the
journal on boot restore, or still live on a durable backend) offsets the
window.
"""

from __future__ import annotations

import logging

from ...resilience.chaos import maybe_kill
from ...resilience.checkpoint import RoundCheckpoint, entry, write_entry
from ..events import DictionaryUpdate, PhaseName
from ..requests import RequestError, StateMachineRequest, SumRequest
from .base import PhaseError, PhaseState, reduce_count_window

logger = logging.getLogger("xaynet.coordinator")


class SumPhase(PhaseState):
    NAME = PhaseName.SUM

    def __init__(self, shared, resume_from: RoundCheckpoint | None = None):
        super().__init__(shared)
        self._sum_dict = None
        self._resume_from = resume_from
        self._journal = shared.settings.resilience.checkpoint_enabled

    async def process(self) -> None:
        params = self.shared.settings.pet.sum
        if self._resume_from is not None:
            # the store dictionary (journal replay, or a durable backend's
            # surviving entries — possibly MORE than the journal recorded:
            # an accepted-but-unjournaled sum participant is still a valid
            # member) offsets the re-opened window
            restored = len(await self.shared.store.coordinator.sum_dict() or {})
            self.arrivals_offset = restored
            params = reduce_count_window(params, restored)
            logger.info(
                "round %d: sum phase RESUMED from journal (%d participants restored)",
                self.shared.round_id,
                restored,
            )
        await self.process_requests(params)
        self._sum_dict = await self.shared.store.coordinator.sum_dict()
        if not self._sum_dict:
            raise PhaseError("NoSumDict", "sum dictionary missing after sum phase")

    def broadcast(self) -> None:
        self.shared.events.broadcast_sum_dict(DictionaryUpdate.new(self._sum_dict))

    async def next(self):
        from .update import UpdatePhase

        return UpdatePhase(self.shared)

    async def handle_request(self, req: StateMachineRequest) -> None:
        if not isinstance(req, SumRequest):
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "not a sum message")
        err = await self.shared.store.coordinator.add_sum_participant(
            req.participant_pk, req.ephm_pk
        )
        if err is not None:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, err.value)
        if self._journal:
            # journal-before-ack: the accepted participant is durable before
            # the acknowledgement leaves (one rewrite per accept; the sum
            # dictionary is tiny relative to the update-phase aggregate)
            sum_dict = await self.shared.store.coordinator.sum_dict() or {}
            await write_entry(self.shared, entry(self.shared, "sum", sum_dict=sum_dict))
        maybe_kill("sum")
