"""Sum2 phase: collect aggregated masks from sum participants.

Reference behavior (rust/xaynet-server/src/state_machine/phases/sum2.rs:33-98):
each accepted ``Sum2Request`` increments the score of the submitted mask
(sum membership and single submission enforced by the store); the model
aggregation is carried forward to Unmask.

Phase overlap (docs/DESIGN.md §22): with ``[overlap] sum2_drain`` the
update phase hands its streaming pipeline over still in flight and this
phase runs the drain barrier in a background executor thread while it
collects sum2 masks — the fold tail that used to serialize behind the
update wall is hidden under this phase's collection wall, recorded as an
``overlap.drain`` span (home phase ``update``) so the round timeline
measures the hidden seconds as negative slack. The drain future is
awaited before the phase exits, so fold errors still fail the round
before Unmask reads the accumulator.

Resilience (docs/DESIGN.md §9): with ``[resilience] checkpoint_enabled``
the phase writes a sum2-tagged journal entry (finished aggregate + sealed
dictionaries) BEFORE acknowledging its first vote, then rewrites it per
accepted vote; ``next`` advances the entry to ``unmask`` before the
finalize barrier so the publish window is covered too. Journal-before-ack
takes precedence over the drain overlap: when both are on, the drain is
awaited before the vote window opens (the base entry needs the exact
aggregate; the overlap win is forfeited for the round's durability).
"""

from __future__ import annotations

import asyncio
import logging
import time

from ...core.mask.serialization import serialize_mask_object
from ...resilience.chaos import maybe_kill
from ...resilience.checkpoint import (
    RoundCheckpoint,
    entry,
    invert_seed_dict,
    write_entry,
)
from ...telemetry import tracing as trace
from ...telemetry.timeline import record_overlap
from ..aggregation import StagedAggregator
from ..events import DictionaryUpdate, PhaseName
from ..requests import RequestError, StateMachineRequest, Sum2Request
from .base import PhaseState, reduce_count_window

logger = logging.getLogger("xaynet.coordinator")

SPAN_OVERLAP_DRAIN = trace.declare_span("overlap.drain")


class Sum2Phase(PhaseState):
    NAME = PhaseName.SUM2

    def __init__(
        self,
        shared,
        aggregator: StagedAggregator,
        resume_from: RoundCheckpoint | None = None,
    ):
        super().__init__(shared)
        self.aggregator = aggregator
        self._drain_task: asyncio.Future | None = None
        self._resume_from = resume_from
        self._journal = shared.settings.resilience.checkpoint_enabled
        # accepted votes in journal form [(sum_pk, serialized mask bytes)];
        # a resumed phase starts from the journaled votes
        self._votes: list = list(resume_from.mask_votes) if resume_from else []
        self._base: RoundCheckpoint | None = None

    def _drain_overlapped(self) -> None:
        """The update pipeline's drain barrier, run under the sum2 wall:
        the hidden seconds land as an ``overlap.drain`` span attributed
        to the update phase (its work), which the timeline fold merges
        into the update interval — the measured negative slack."""
        t0 = time.monotonic()
        try:
            self.aggregator.drain()
        finally:
            dt = time.monotonic() - t0
            trace.get_tracer().record_span(
                SPAN_OVERLAP_DRAIN,
                start=t0,
                duration=dt,
                phase="update",
                tenant=self.shared.tenant,
            )
            record_overlap("drain", dt, tenant=self.shared.tenant)

    async def process(self) -> None:
        params = self.shared.settings.pet.sum2
        if self.shared.settings.overlap.feature("sum2_drain"):
            self._drain_task = asyncio.get_running_loop().run_in_executor(
                None, self._drain_overlapped
            )
        if self._journal and self._drain_task is not None:
            # journal-ready-before-first-vote-ack: the base entry snapshots
            # the finished aggregate, so the drain must complete BEFORE the
            # window opens — durability outranks the overlap win here
            task, self._drain_task = self._drain_task, None
            await task
        if self._journal:
            if self._resume_from is not None:
                await self._rebroadcast_dicts()
                self.arrivals_offset = len(self._votes)
                params = reduce_count_window(params, len(self._votes))
                self._base = self._resume_from
                logger.info(
                    "round %d: sum2 phase RESUMED from journal (%d votes restored)",
                    self.shared.round_id,
                    len(self._votes),
                )
            else:
                await self._build_base()
        try:
            await self.process_requests(params)
        finally:
            if self._drain_task is not None:
                # the overlap window closes with the phase: fold errors
                # surface HERE (failing the round exactly where the
                # serial flow's drain would have), never past sum2
                task, self._drain_task = self._drain_task, None
                await task

    async def _rebroadcast_dicts(self) -> None:
        """Participants contacting a restarted coordinator need the round
        dictionaries re-broadcast: the seed dict drives the sum2 mask
        computation the re-opened window is waiting for."""
        coord = self.shared.store.coordinator
        sum_dict = await coord.sum_dict()
        if sum_dict:
            self.shared.events.broadcast_sum_dict(DictionaryUpdate.new(sum_dict))
        seed_dict = await coord.seed_dict()
        if seed_dict:
            self.shared.events.broadcast_seed_dict(DictionaryUpdate.new(seed_dict))

    async def _build_base(self) -> None:
        """Journal the Update -> Sum2 transition: the finished aggregate +
        the sealed dictionaries, written before the first vote is acked."""
        loop = asyncio.get_running_loop()
        # drain + snapshot off the event loop (blocks on in-flight folds)
        snap = await loop.run_in_executor(None, self.aggregator.snapshot_journal)
        coord = self.shared.store.coordinator
        sum_dict = await coord.sum_dict() or {}
        seed_dicts = invert_seed_dict(await coord.seed_dict())
        self._base = entry(
            self.shared,
            "sum2",
            snap,
            sum_dict=sum_dict,
            seed_dicts=seed_dicts,
            mask_votes=self._votes,
        )
        await write_entry(self.shared, self._base)

    def broadcast(self) -> None:
        # the round's dictionaries are spent once the masks are in
        # (reference: sum2.rs invalidates the dicts on exit)
        self.shared.events.broadcast_sum_dict(DictionaryUpdate.invalidate())
        self.shared.events.broadcast_seed_dict(DictionaryUpdate.invalidate())

    async def next(self):
        from .unmask import Unmask

        if self._base is not None:
            # advance the journal into the publish window BEFORE the
            # finalize barrier: a crash anywhere from here to the journal
            # retire in Unmask resumes into Unmask with the final votes
            self._base.phase = "unmask"
            self._base.mask_votes = list(self._votes)
            await write_entry(self.shared, self._base)
        # finalize WITHOUT gathering: device rounds hand Unmask a sharded
        # view so the elected mask is subtracted per-shard in place (host
        # rounds get the host Aggregation exactly as before); with
        # [overlap] eager_unmask the pipeline stays open so each shard
        # subtracts at its own last-fold commit (docs/DESIGN.md §22)
        eager = self.shared.settings.overlap.feature("eager_unmask")
        return Unmask(self.shared, self.aggregator.finalize_inplace(defer_drain=eager))

    async def handle_request(self, req: StateMachineRequest) -> None:
        if not isinstance(req, Sum2Request):
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "not a sum2 message")
        err = await self.shared.store.coordinator.incr_mask_score(
            req.participant_pk, req.model_mask
        )
        if err is not None:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, err.value)
        if self._base is not None:
            # journal-before-ack: the accepted vote is durable before the
            # acknowledgement leaves (rewrite; votes are mask-sized)
            self._votes.append(
                (req.participant_pk, serialize_mask_object(req.model_mask))
            )
            self._base.mask_votes = list(self._votes)
            await write_entry(self.shared, self._base)
        maybe_kill("sum2")
