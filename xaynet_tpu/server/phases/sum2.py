"""Sum2 phase: collect aggregated masks from sum participants.

Reference behavior (rust/xaynet-server/src/state_machine/phases/sum2.rs:33-98):
each accepted ``Sum2Request`` increments the score of the submitted mask
(sum membership and single submission enforced by the store); the model
aggregation is carried forward to Unmask.
"""

from __future__ import annotations

from ..aggregation import StagedAggregator
from ..events import DictionaryUpdate, PhaseName
from ..requests import RequestError, StateMachineRequest, Sum2Request
from .base import PhaseState


class Sum2Phase(PhaseState):
    NAME = PhaseName.SUM2

    def __init__(self, shared, aggregator: StagedAggregator):
        super().__init__(shared)
        self.aggregator = aggregator

    async def process(self) -> None:
        await self.process_requests(self.shared.settings.pet.sum2)

    def broadcast(self) -> None:
        # the round's dictionaries are spent once the masks are in
        # (reference: sum2.rs invalidates the dicts on exit)
        self.shared.events.broadcast_sum_dict(DictionaryUpdate.invalidate())
        self.shared.events.broadcast_seed_dict(DictionaryUpdate.invalidate())

    async def next(self):
        from .unmask import Unmask

        # finalize WITHOUT gathering: device rounds hand Unmask a sharded
        # view so the elected mask is subtracted per-shard in place (host
        # rounds get the host Aggregation exactly as before)
        return Unmask(self.shared, self.aggregator.finalize_inplace())

    async def handle_request(self, req: StateMachineRequest) -> None:
        if not isinstance(req, Sum2Request):
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "not a sum2 message")
        err = await self.shared.store.coordinator.incr_mask_score(
            req.participant_pk, req.model_mask
        )
        if err is not None:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, err.value)
