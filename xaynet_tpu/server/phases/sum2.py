"""Sum2 phase: collect aggregated masks from sum participants.

Reference behavior (rust/xaynet-server/src/state_machine/phases/sum2.rs:33-98):
each accepted ``Sum2Request`` increments the score of the submitted mask
(sum membership and single submission enforced by the store); the model
aggregation is carried forward to Unmask.

Phase overlap (docs/DESIGN.md §22): with ``[overlap] sum2_drain`` the
update phase hands its streaming pipeline over still in flight and this
phase runs the drain barrier in a background executor thread while it
collects sum2 masks — the fold tail that used to serialize behind the
update wall is hidden under this phase's collection wall, recorded as an
``overlap.drain`` span (home phase ``update``) so the round timeline
measures the hidden seconds as negative slack. The drain future is
awaited before the phase exits, so fold errors still fail the round
before Unmask reads the accumulator.
"""

from __future__ import annotations

import asyncio
import time

from ...telemetry import tracing as trace
from ...telemetry.timeline import record_overlap
from ..aggregation import StagedAggregator
from ..events import DictionaryUpdate, PhaseName
from ..requests import RequestError, StateMachineRequest, Sum2Request
from .base import PhaseState

SPAN_OVERLAP_DRAIN = trace.declare_span("overlap.drain")


class Sum2Phase(PhaseState):
    NAME = PhaseName.SUM2

    def __init__(self, shared, aggregator: StagedAggregator):
        super().__init__(shared)
        self.aggregator = aggregator
        self._drain_task: asyncio.Future | None = None

    def _drain_overlapped(self) -> None:
        """The update pipeline's drain barrier, run under the sum2 wall:
        the hidden seconds land as an ``overlap.drain`` span attributed
        to the update phase (its work), which the timeline fold merges
        into the update interval — the measured negative slack."""
        t0 = time.monotonic()
        try:
            self.aggregator.drain()
        finally:
            dt = time.monotonic() - t0
            trace.get_tracer().record_span(
                SPAN_OVERLAP_DRAIN,
                start=t0,
                duration=dt,
                phase="update",
                tenant=self.shared.tenant,
            )
            record_overlap("drain", dt, tenant=self.shared.tenant)

    async def process(self) -> None:
        if self.shared.settings.overlap.feature("sum2_drain"):
            self._drain_task = asyncio.get_running_loop().run_in_executor(
                None, self._drain_overlapped
            )
        try:
            await self.process_requests(self.shared.settings.pet.sum2)
        finally:
            if self._drain_task is not None:
                # the overlap window closes with the phase: fold errors
                # surface HERE (failing the round exactly where the
                # serial flow's drain would have), never past sum2
                task, self._drain_task = self._drain_task, None
                await task

    def broadcast(self) -> None:
        # the round's dictionaries are spent once the masks are in
        # (reference: sum2.rs invalidates the dicts on exit)
        self.shared.events.broadcast_sum_dict(DictionaryUpdate.invalidate())
        self.shared.events.broadcast_seed_dict(DictionaryUpdate.invalidate())

    async def next(self):
        from .unmask import Unmask

        # finalize WITHOUT gathering: device rounds hand Unmask a sharded
        # view so the elected mask is subtracted per-shard in place (host
        # rounds get the host Aggregation exactly as before); with
        # [overlap] eager_unmask the pipeline stays open so each shard
        # subtracts at its own last-fold commit (docs/DESIGN.md §22)
        eager = self.shared.settings.overlap.feature("eager_unmask")
        return Unmask(self.shared, self.aggregator.finalize_inplace(defer_drain=eager))

    async def handle_request(self, req: StateMachineRequest) -> None:
        if not isinstance(req, Sum2Request):
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "not a sum2 message")
        err = await self.shared.store.coordinator.incr_mask_score(
            req.participant_pk, req.model_mask
        )
        if err is not None:
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, err.value)
