"""Coordinator runtime: state machine, services, REST API, settings, metrics.

Reference surface: rust/xaynet-server/src/ (state_machine, services, rest,
settings, metrics); see docs/PARITY.md for the component-level map.
"""
