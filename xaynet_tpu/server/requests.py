"""Request channel between the services and the state machine.

Functional port of the reference's request plumbing (reference:
rust/xaynet-server/src/state_machine/requests.rs:27-205): services submit
typed requests over an unbounded queue; each request carries a one-shot
response future resolved by the phase that handles it. Requests from prior
phases are purged with a rejection at phase end.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from enum import Enum
from typing import Optional, Union

from ..utils import tracing

from ..core.common import LocalSeedDict
from ..core.mask.object import MaskObject
from ..core.message import Message, Sum, Sum2, Update
from ..telemetry.registry import get_registry

# depth of the services -> state-machine queue: the leading indicator of a
# phase falling behind its ingest (scraped via GET /metrics). Labelled per
# TENANT: each tenant runs its own channel, and one tenant's close/purge
# must never zero (or double-count into) another tenant's depth — the
# cross-tenant isolation contract of docs/DESIGN.md §19.
_QUEUE_DEPTH = get_registry().gauge(
    "xaynet_request_queue_depth",
    "State-machine requests enqueued and not yet handled by a phase, "
    "by tenant.",
    ("tenant",),
)


class RequestError(Exception):
    """A request was rejected by the state machine."""

    class Kind(str, Enum):
        MESSAGE_REJECTED = "the message was rejected"
        MESSAGE_DISCARDED = "the message was discarded"
        INTERNAL = "internal error"

    def __init__(self, kind: "RequestError.Kind", detail: str = ""):
        super().__init__(f"{kind.value}{': ' + detail if detail else ''}")
        self.kind = kind


@dataclass
class SumRequest:
    participant_pk: bytes
    ephm_pk: bytes


@dataclass
class UpdateRequest:
    participant_pk: bytes
    local_seed_dict: LocalSeedDict
    masked_model: MaskObject


@dataclass
class Sum2Request:
    participant_pk: bytes
    model_mask: MaskObject


@dataclass
class CoalescedUpdates:
    """A micro-batch of verified ``UpdateRequest``s travelling as ONE
    channel envelope (built by ``ingest.coalescer``).

    Each member keeps its own response future: the phase resolves them
    individually, so one rejected update never fails its batch-mates, and
    the seed-dict insert stays paired with its masked model per member.
    ``request_ids`` (parallel to ``members``, optional) preserves each
    message's tracing id through the batch.
    """

    members: list[UpdateRequest]
    responses: list[asyncio.Future]
    request_ids: Optional[list[str]] = None

    def __len__(self) -> int:
        return len(self.members)

    def envelopes(self, fallback_request_id: str = "-"):
        """One per-member ``_Envelope``, carrying the member's own tracing
        id (so batched log lines keep per-message correlation)."""
        ids = self.request_ids or [fallback_request_id] * len(self.members)
        return [
            _Envelope(req, fut, rid)
            for req, fut, rid in zip(self.members, self.responses, ids)
        ]

    def reject_members(self, error: Exception) -> None:
        """Resolve every still-pending member future with ``error`` (purge
        at phase end, channel shutdown, infrastructure failure)."""
        for fut in self.responses:
            if not fut.done():
                fut.set_exception(error)


@dataclass
class PartialAggregate:
    """An edge aggregator's pre-folded window: the modular sum of
    ``len(members)`` verified masked updates plus every member's seed dict,
    travelling upstream as ONE envelope (``xaynet_tpu.edge``).

    The envelope is ATOMIC: the update phase folds it as a single
    ``masked_add`` dispatch and advances ``nb_models`` by the member count
    with all seed dicts inserted, or rejects it whole — it is never split
    across a window boundary or a degraded close. ``(edge_id, window_seq)``
    is the per-edge watermark: a redelivered envelope (the edge retried
    after a lost acknowledgement) is rejected as stale instead of folded
    twice, which would break the nb_models == seed-watermark invariant.
    """

    edge_id: str
    window_seq: int
    round_seed: bytes
    members: list[bytes]  # update participant pks, envelope order
    seed_dicts: dict[bytes, LocalSeedDict]  # update pk -> local seed dict
    masked: MaskObject  # modular sum of the members' masked models
    # the shipping edge's trace context ("trace_id-span_id", the envelope's
    # `trace` header field): the update phase's fold span adopts the trace
    # id so a two-tier round stitches into ONE trace (docs/DESIGN.md §16)
    trace: Optional[str] = None

    def __len__(self) -> int:
        return len(self.members)


class EnvelopeReplay(Exception):
    """The EXACT envelope at the per-edge watermark was redelivered — the
    edge retried after a lost acknowledgement, and everything it carries is
    already folded. The phase answers SUCCESS without folding or advancing
    the count window (idempotent ack), so the edge does not misreport a
    folded envelope as rejected data loss."""


StateMachineRequest = Union[
    SumRequest, UpdateRequest, Sum2Request, CoalescedUpdates, PartialAggregate
]


def request_from_message(message: Message) -> StateMachineRequest:
    """Converts a verified message into a state-machine request
    (reference: requests.rs:88-114)."""
    payload = message.payload
    if isinstance(payload, Sum):
        return SumRequest(participant_pk=message.participant_pk, ephm_pk=payload.ephm_pk)
    if isinstance(payload, Update):
        return UpdateRequest(
            participant_pk=message.participant_pk,
            local_seed_dict=payload.local_seed_dict,
            masked_model=payload.masked_model,
        )
    if isinstance(payload, Sum2):
        return Sum2Request(participant_pk=message.participant_pk, model_mask=payload.model_mask)
    raise ValueError(f"cannot convert payload {type(payload)} into a request")


@dataclass
class _Envelope:
    request: StateMachineRequest
    response: asyncio.Future
    request_id: str = "-"


class RequestReceiver:
    """The state machine's end of the request channel.

    ``maxsize`` bounds the channel (0 = unbounded, the historical default;
    deployments running the admission-controlled ingest pipeline are bounded
    upstream by the intake shards). The depth gauge tracks REAL envelopes
    only — the shutdown sentinel is never counted — and is kept in sync on
    enqueue, dequeue, phase-end purge (via ``try_recv``) and close.
    """

    def __init__(self, maxsize: int = 0, tenant: str = "default"):
        # one queue carries both envelopes and the single shutdown sentinel;
        # the +1 slack below keeps a full bounded channel closable
        self._queue: asyncio.Queue[Optional[_Envelope]] = (
            # unbounded only on request: ingest deployments bound upstream
            asyncio.Queue()  # lint: unbounded-ok
            if maxsize <= 0
            else asyncio.Queue(maxsize + 1)
        )
        self.maxsize = maxsize
        self.tenant = tenant
        self._gauge = _QUEUE_DEPTH.labels(tenant=tenant)
        self._depth = 0
        self._closed = False

    def _enqueue(self, env: _Envelope) -> None:
        if self._closed:
            raise RequestError(RequestError.Kind.INTERNAL, "state machine is shut down")
        if self.maxsize and self._depth >= self.maxsize:
            raise RequestError(RequestError.Kind.INTERNAL, "request channel full")
        self._queue.put_nowait(env)
        self._depth += 1
        self._gauge.set(self._depth)

    def _dequeued(self, env: Optional[_Envelope]) -> Optional[_Envelope]:
        if env is not None:
            self._depth -= 1
            self._gauge.set(self._depth)
        return env

    async def next_request(self) -> _Envelope:
        env = self._dequeued(await self._queue.get())
        if env is None:
            raise ChannelClosed()
        return env

    def try_recv(self) -> Optional[_Envelope]:
        """Non-blocking receive; None when the queue is momentarily empty."""
        try:
            env = self._queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        env = self._dequeued(env)
        if env is None:
            raise ChannelClosed()
        return env

    def close(self) -> None:
        """Shut the channel: every queued request is rejected immediately so
        an in-flight ``request()`` can never hang on a dead state machine.

        Scope: strictly THIS channel. The purge resolves only futures
        queued here, and only this tenant's depth gauge child zeroes —
        closing one tenant's channel must never strand or misaccount
        another tenant's in-flight requests (docs/DESIGN.md §19)."""
        if self._closed:
            return
        self._closed = True
        error = RequestError(RequestError.Kind.INTERNAL, "state machine is shut down")
        while True:
            try:
                env = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if env is None:
                continue
            if isinstance(env.request, CoalescedUpdates):
                env.request.reject_members(error)
            if not env.response.done():
                env.response.set_exception(error)
        self._depth = 0
        self._gauge.set(0)
        self._queue.put_nowait(None)

    def sender(self) -> "RequestSender":
        return RequestSender(self)


class ChannelClosed(Exception):
    """The request channel was shut down."""


class RequestSender:
    """The services' end of the request channel (cloneable)."""

    def __init__(self, receiver: RequestReceiver):
        self._receiver = receiver

    def close(self) -> None:
        """Shut the channel from the services' side.

        The runner uses this on the cancel path: a cancelled state machine
        never reaches the Shutdown phase (which closes the channel in normal
        termination), and draining components — the ingest pipeline's final
        coalescer flush in particular — must fail fast instead of awaiting a
        request nobody will ever handle.
        """
        self._receiver.close()

    async def request(self, req: StateMachineRequest) -> None:
        """Submit a request and await the state machine's verdict.

        Raises ``RequestError`` when the request is rejected/discarded.
        """
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._receiver._enqueue(_Envelope(req, fut, tracing.current_request_id()))
        await fut
