"""Request channel between the services and the state machine.

Functional port of the reference's request plumbing (reference:
rust/xaynet-server/src/state_machine/requests.rs:27-205): services submit
typed requests over an unbounded queue; each request carries a one-shot
response future resolved by the phase that handles it. Requests from prior
phases are purged with a rejection at phase end.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from enum import Enum
from typing import Optional, Union

from ..utils import tracing

from ..core.common import LocalSeedDict
from ..core.mask.object import MaskObject
from ..core.message import Message, Sum, Sum2, Update
from ..telemetry.registry import get_registry

# depth of the services -> state-machine queue: the leading indicator of a
# phase falling behind its ingest (scraped via GET /metrics)
_QUEUE_DEPTH = get_registry().gauge(
    "xaynet_request_queue_depth",
    "State-machine requests enqueued and not yet handled by a phase.",
)


class RequestError(Exception):
    """A request was rejected by the state machine."""

    class Kind(str, Enum):
        MESSAGE_REJECTED = "the message was rejected"
        MESSAGE_DISCARDED = "the message was discarded"
        INTERNAL = "internal error"

    def __init__(self, kind: "RequestError.Kind", detail: str = ""):
        super().__init__(f"{kind.value}{': ' + detail if detail else ''}")
        self.kind = kind


@dataclass
class SumRequest:
    participant_pk: bytes
    ephm_pk: bytes


@dataclass
class UpdateRequest:
    participant_pk: bytes
    local_seed_dict: LocalSeedDict
    masked_model: MaskObject


@dataclass
class Sum2Request:
    participant_pk: bytes
    model_mask: MaskObject


StateMachineRequest = Union[SumRequest, UpdateRequest, Sum2Request]


def request_from_message(message: Message) -> StateMachineRequest:
    """Converts a verified message into a state-machine request
    (reference: requests.rs:88-114)."""
    payload = message.payload
    if isinstance(payload, Sum):
        return SumRequest(participant_pk=message.participant_pk, ephm_pk=payload.ephm_pk)
    if isinstance(payload, Update):
        return UpdateRequest(
            participant_pk=message.participant_pk,
            local_seed_dict=payload.local_seed_dict,
            masked_model=payload.masked_model,
        )
    if isinstance(payload, Sum2):
        return Sum2Request(participant_pk=message.participant_pk, model_mask=payload.model_mask)
    raise ValueError(f"cannot convert payload {type(payload)} into a request")


@dataclass
class _Envelope:
    request: StateMachineRequest
    response: asyncio.Future
    request_id: str = "-"


class RequestReceiver:
    """The state machine's end of the request channel."""

    def __init__(self):
        self._queue: asyncio.Queue[Optional[_Envelope]] = asyncio.Queue()
        self._closed = False

    async def next_request(self) -> _Envelope:
        env = await self._queue.get()
        _QUEUE_DEPTH.set(self._queue.qsize())
        if env is None:
            raise ChannelClosed()
        return env

    def try_recv(self) -> Optional[_Envelope]:
        """Non-blocking receive; None when the queue is momentarily empty."""
        try:
            env = self._queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        _QUEUE_DEPTH.set(self._queue.qsize())
        if env is None:
            raise ChannelClosed()
        return env

    def close(self) -> None:
        self._closed = True
        self._queue.put_nowait(None)

    def sender(self) -> "RequestSender":
        return RequestSender(self)


class ChannelClosed(Exception):
    """The request channel was shut down."""


class RequestSender:
    """The services' end of the request channel (cloneable)."""

    def __init__(self, receiver: RequestReceiver):
        self._receiver = receiver

    async def request(self, req: StateMachineRequest) -> None:
        """Submit a request and await the state machine's verdict.

        Raises ``RequestError`` when the request is rejected/discarded.
        """
        if self._receiver._closed:
            raise RequestError(RequestError.Kind.INTERNAL, "state machine is shut down")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._receiver._queue.put_nowait(_Envelope(req, fut, tracing.current_request_id()))
        _QUEUE_DEPTH.set(self._receiver._queue.qsize())
        await fut
