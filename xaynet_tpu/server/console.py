"""Live operator console: the ``GET /statusz`` HTML page (docs/DESIGN.md §20).

One self-contained page rendered entirely from in-process telemetry state —
the metrics registry, the round-wall timeline (``telemetry.timeline``) and
the SLO engine (``telemetry.slo``) — so an operator gets the coordinator's
live picture from a browser with no scrape pipeline in between:

- per-tenant round/phase state with the recent round-wall **sparkline** and
  the last round's phase decomposition (wall / self time / overlap);
- the shared accumulator pool's page occupancy and per-tenant lease balance
  (multi-tenant deployments, §19);
- the streaming-fold pipeline's overlap ratio and degraded shards (§15);
- live SLO burn rates / budget remaining and the recent-alert ring.

Rendering is stdlib-only string assembly (no template engine, and — like
the whole REST layer — no jax import: everything here reads gauges and
bounded in-memory rings). ``render_statusz`` and ``alerts_payload`` are
declared taint sinks (§18): the alert entries they serialize were scrubbed
when stored, and every dynamic string is HTML-escaped before it lands in
the page.
"""

from __future__ import annotations

import html
import time

from ..telemetry.slo import SLOS, get_engine
from ..telemetry.timeline import get_timeline

# eight-level unicode sparkline ramp for the recent-wall strip
_SPARK_RAMP = "▁▂▃▄▅▆▇█"

_STYLE = """
body { font-family: ui-monospace, SFMono-Regular, Menlo, monospace;
       margin: 1.5rem; color: #222; background: #fafafa; }
h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.4rem; }
table { border-collapse: collapse; margin: 0.4rem 0; }
th, td { border: 1px solid #ccc; padding: 0.25rem 0.6rem; text-align: left; }
th { background: #eee; }
.spark { font-size: 1.1rem; letter-spacing: 1px; color: #369; }
.ok { color: #2a7; } .warn { color: #b80; font-weight: bold; }
.page { color: #c22; font-weight: bold; }
.degraded { color: #c22; }
.muted { color: #888; }
"""


def _esc(value) -> str:
    return html.escape(str(value), quote=True)


def _sparkline(walls: list[tuple[int, float]]) -> str:
    """Unicode sparkline over recent (round_id, wall_s) pairs, oldest
    first; scaled to the window's own min/max so shape survives any
    absolute magnitude."""
    values = [w for _, w in walls]
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARK_RAMP[0] * len(values)
    return "".join(
        _SPARK_RAMP[min(len(_SPARK_RAMP) - 1, int((v - lo) / span * len(_SPARK_RAMP)))]
        for v in values
    )


def _severity_class(severity: str) -> str:
    return severity if severity in ("warn", "page") else "ok"


def _lifecycle_states() -> dict:
    """Tenant lifecycle states for the table column (§23); empty when no
    lifecycle manager is installed (single-tenant deployments)."""
    from ..tenancy.lifecycle import get_manager  # lazy: keeps import cycle out

    manager = get_manager()
    if manager is None:
        return {}
    try:
        return manager.states()
    except Exception:
        return {}


def _tenant_rows(server) -> str:
    """Per-tenant state table rows: lifecycle, phase, round, last wall +
    sparkline, degraded flag and the three SLO burn rates."""
    timeline = get_timeline()
    engine = get_engine()
    lifecycle = _lifecycle_states()
    routes_by_tenant = {"default": server._default_routes, **server.tenants}
    # tenants the timeline folded but the REST layer doesn't route (edge
    # processes, tests driving the fold directly) still get a row
    for tenant in timeline.tenants():
        routes_by_tenant.setdefault(tenant, None)
    rows = []
    for tenant in sorted(routes_by_tenant):
        routes = routes_by_tenant[tenant]
        if routes is not None:
            phase = routes.fetcher.phase().value
            round_id = routes.fetcher.events.params.get_latest().round_id
        else:
            phase, round_id = "-", "-"
        last = timeline.last(tenant)
        walls = timeline.recent_walls(tenant)
        wall = f"{last['wall_s']:.3f}s" if last else "-"
        degraded = (
            '<span class="degraded">degraded</span>'
            if last and last.get("degraded")
            else '<span class="ok">full</span>' if last else "-"
        )
        # cross-phase overlap of the last folded round (docs/DESIGN.md
        # §22): negative slack — the round wall came in under the serial
        # sum of phase walls — is the overlap engine's visible win
        if last:
            ov = last.get("overlap_s", 0.0)
            slack = last.get("wall_s", 0.0) - sum(
                p.get("wall_s", 0.0) for p in last.get("phases", {}).values()
            )
            overlap_cell = "{:.3f}s{}".format(
                ov, " <span class='ok'>(−slack)</span>" if slack < 0 else ""
            )
        else:
            overlap_cell = "-"
        burns = engine.burn_snapshot(tenant)
        burn_cells = "".join(
            "<td>{}</td>".format(
                "{:.2f}x / {:.0%} left".format(
                    burns[slo]["burn_rate"], max(0.0, burns[slo]["budget_remaining"])
                )
                if slo in burns
                else '<span class="muted">-</span>'
            )
            for slo in SLOS
        )
        state = lifecycle.get(tenant, "")
        state_cell = (
            '<span class="{cls}">{st}</span>'.format(
                cls="ok" if state == "serving" else "warn" if state == "onboarding" else "page",
                st=_esc(state),
            )
            if state
            else '<span class="muted">-</span>'
        )
        rows.append(
            "<tr><td>{t}</td><td>{lc}</td><td>{p}</td><td>{r}</td><td>{w}</td>"
            '<td class="spark">{s}</td><td>{o}</td><td>{d}</td>{b}</tr>'.format(
                t=_esc(tenant),
                lc=state_cell,
                p=_esc(phase),
                r=_esc(round_id),
                w=_esc(wall),
                s=_sparkline(walls),
                o=overlap_cell,
                d=degraded,
                b=burn_cells,
            )
        )
    return "\n".join(rows)


def _decomposition_section(tenant: str) -> str:
    """The last folded round's phase decomposition for one tenant."""
    last = get_timeline().last(tenant)
    if not last:
        return ""
    phase_rows = "".join(
        "<tr><td>{p}</td><td>{w:.4f}s</td><td>{s:.4f}s</td></tr>".format(
            p=_esc(phase), w=vals["wall_s"], s=vals["self_s"]
        )
        for phase, vals in last.get("phases", {}).items()
    )
    slow_rows = "".join(
        "<tr><td>{n}</td><td>{d:.4f}s</td></tr>".format(
            n=_esc(entry["span"]), d=entry["seconds"]
        )
        for entry in last.get("slowest", ())
    )
    return (
        "<h2>round {rid} — {tenant}</h2>"
        "<p>wall <b>{wall:.3f}s</b>, overlap {ov:.3f}s "
        "({ratio:.0%}), gap {gap:.3f}s, {spans} spans</p>"
        "<table><tr><th>phase</th><th>wall</th><th>self</th></tr>{rows}</table>"
        "<table><tr><th>slowest span</th><th>seconds</th></tr>{slow}</table>"
    ).format(
        rid=_esc(last["round_id"]),
        tenant=_esc(tenant),
        wall=last["wall_s"],
        ov=last["overlap_s"],
        ratio=last["overlap_ratio"],
        gap=last["gap_s"],
        spans=last["spans"],
        rows=phase_rows,
        slow=slow_rows,
    )


def _pool_section(server) -> str:
    """Accumulator-pool occupancy + per-tenant lease balance (§19); empty
    for single-tenant deployments (no pool to report)."""
    if not server.tenants:
        return ""
    from ..tenancy.pool import get_pool  # lazy: single-tenant paths never pay it

    stats = get_pool().stats()
    leases = stats.get("tenant_leases") or {}
    lease_rows = "".join(
        "<tr><td>{t}</td><td>{n}</td></tr>".format(t=_esc(t), n=_esc(n))
        for t, n in sorted(leases.items())
    )
    occupancy = "".join(
        "<tr><td>{k}</td><td>{v}</td></tr>".format(k=_esc(k), v=_esc(stats[k]))
        for k in (
            "page_bytes",
            "slabs",
            "host_pages_in_use",
            "host_pages_free",
            "device_pages_in_use",
            "fragmentation",
        )
        if k in stats
    )
    return (
        "<h2>accumulator pool</h2>"
        "<table><tr><th>stat</th><th>value</th></tr>{occ}</table>"
        "<table><tr><th>tenant</th><th>pages leased</th></tr>{leases}</table>"
    ).format(occ=occupancy, leases=lease_rows or '<tr><td colspan="2" class="muted">none</td></tr>')


def _streaming_section(server) -> str:
    """Streaming-fold pipeline overlap + degraded shards (§15), from the
    same registry reads as the /healthz section; empty when no streaming
    pipeline ever ran in this process."""
    section = server._streaming_health()
    if section is None:
        return ""
    shards = section.pop("shards", {})
    shard_rows = "".join(
        '<tr><td>{s}</td><td>{o:.2f}</td><td>{d}</td><td>{f}</td></tr>'.format(
            s=_esc(shard),
            o=vals.get("overlap_ratio", 0.0),
            d=_esc(vals.get("staging_depth", 0)),
            f=_esc(vals.get("inflight_folds", 0)),
        )
        for shard, vals in shards.items()
    )
    degraded = (
        '<span class="degraded">degraded</span>'
        if section["degraded"]
        else '<span class="ok">nominal</span>'
    )
    out = (
        "<h2>streaming pipeline</h2>"
        "<p>{deg} — overlap {ov:.2f}, staging depth {depth}, "
        "in-flight folds {folds}</p>"
    ).format(
        deg=degraded,
        ov=section["overlap_ratio"],
        depth=_esc(section["staging_depth"]),
        folds=_esc(section["inflight_folds"]),
    )
    if shard_rows:
        out += (
            "<table><tr><th>shard</th><th>overlap</th><th>staging</th>"
            "<th>in-flight</th></tr>{rows}</table>"
        ).format(rows=shard_rows)
    return out


def _ingress_section(server) -> str:
    """Coordinator-ingress state (§21): per-tenant accepted/shed rates,
    intake shard occupancy and the accepted wire-format mix, read straight
    off each tenant's ingest pipeline; empty when no pipeline is wired
    (direct-handler deployments)."""
    routes_by_tenant = {"default": server._default_routes, **server.tenants}
    rows = []
    for tenant in sorted(routes_by_tenant):
        pipeline = getattr(routes_by_tenant[tenant], "pipeline", None)
        if pipeline is None:
            continue
        stats = pipeline.ingress_stats()
        wire = stats["wire"]
        occupancy = stats["shard_occupancy"]
        rows.append(
            "<tr><td>{t}</td><td>{aps:.1f}/s</td><td>{at}</td>"
            "<td>{sps:.1f}/s</td><td>{st}</td><td>{rt}</td>"
            "<td>{occ}</td><td>{pk} / {lg}</td></tr>".format(
                t=_esc(tenant),
                aps=stats["accepted_per_s"],
                at=_esc(stats["accepted_total"]),
                sps=stats["shed_per_s"],
                st=_esc(stats["shed_total"]),
                rt=_esc(stats["rejected_total"]),
                occ=_esc(" ".join(str(o) for o in occupancy)),
                pk=_esc(wire.get("packed", 0)),
                lg=_esc(wire.get("legacy", 0)),
            )
        )
    if not rows:
        return ""
    return (
        "<h2>ingress</h2>"
        "<table><tr><th>tenant</th><th>accepted/s</th><th>accepted</th>"
        "<th>shed/s</th><th>shed</th><th>rejected</th>"
        "<th>shard occupancy</th><th>wire packed/legacy</th></tr>"
        "{rows}</table>".format(rows="".join(rows))
    )


def _alerts_section() -> str:
    """Active alerts banner + the recent-transition ring, newest first."""
    engine = get_engine()
    active = engine.active_alerts()
    banner = (
        "".join(
            '<p class="{cls}">FIRING: tenant {t} {slo} — {sev}</p>'.format(
                cls=_severity_class(a["severity"]),
                t=_esc(a["tenant"]),
                slo=_esc(a["slo"]),
                sev=_esc(a["severity"]),
            )
            for a in active
        )
        or '<p class="ok">no active alerts</p>'
    )
    rows = "".join(
        '<tr><td>{ts}</td><td>{t}</td><td>{slo}</td>'
        '<td class="{cls}">{sev}</td><td>{prev}</td><td>{r}</td>'
        "<td>{bf}x</td><td>{bs}x</td></tr>".format(
            ts=_esc(time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0)))),
            t=_esc(e.get("tenant", "")),
            slo=_esc(e.get("slo", "")),
            cls=_severity_class(e.get("severity", "")),
            sev=_esc(e.get("severity", "")),
            prev=_esc(e.get("previous", "")),
            r=_esc(e.get("round_id", "")),
            bf=_esc(e.get("burn_fast", "")),
            bs=_esc(e.get("burn_slow", "")),
        )
        for e in reversed(engine.recent_alerts())
    )
    table = (
        "<table><tr><th>time</th><th>tenant</th><th>slo</th><th>severity</th>"
        "<th>previous</th><th>round</th><th>fast</th><th>slow</th></tr>"
        "{rows}</table>".format(rows=rows)
        if rows
        else '<p class="muted">no transitions recorded</p>'
    )
    return "<h2>alerts</h2>" + banner + table


def render_statusz(server) -> str:
    """Assemble the full ``/statusz`` page from live telemetry state.

    ``server`` is the :class:`..rest.RestServer` — the console reads its
    tenant routing table and reuses its registry-backed health readers;
    everything else comes from the process-wide timeline/SLO singletons.
    Declared as a taint sink (§18): all dynamic content is escaped here
    and alert entries were scrubbed at store time.
    """
    timeline = get_timeline()
    uptime = time.monotonic() - server._started_at
    tenant_labels = sorted({"default", *server.tenants, *timeline.tenants()})
    burn_headers = "".join(f"<th>{_esc(slo)} burn</th>" for slo in SLOS)
    sections = [
        "<h1>xaynet-tpu coordinator</h1>",
        '<p class="muted">uptime {up:.0f}s — {rounds} rounds folded — '
        "generated {ts}</p>".format(
            up=uptime,
            rounds=timeline.rounds_folded(),
            ts=_esc(time.strftime("%Y-%m-%d %H:%M:%S")),
        ),
        _alerts_section(),
        "<h2>tenants</h2>",
        "<table><tr><th>tenant</th><th>lifecycle</th><th>phase</th><th>round</th><th>wall</th>"
        "<th>recent walls</th><th>overlap</th><th>windows</th>{bh}</tr>{rows}</table>".format(
            bh=burn_headers, rows=_tenant_rows(server)
        ),
    ]
    for tenant in tenant_labels:
        sections.append(_decomposition_section(tenant))
    sections.append(_ingress_section(server))
    sections.append(_pool_section(server))
    sections.append(_streaming_section(server))
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>xaynet-tpu statusz</title>"
        f"<style>{_STYLE}</style></head><body>"
        + "".join(s for s in sections if s)
        + "</body></html>"
    )
