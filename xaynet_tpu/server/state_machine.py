"""The coordinator state machine and its initializer.

Reference surface: rust/xaynet-server/src/state_machine/mod.rs:124-180 (the
phase loop) and initializer.rs:97-281 (fresh start vs. restore-from-store
with model-length validation).
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from ..resilience import checkpoint as ckpt_mod
from ..storage.traits import Store
from ..telemetry.bridge import BridgedMetrics
from .coordinator import CoordinatorState
from .events import EventPublisher, EventSubscriber, ModelUpdate, PhaseName
from .phases import Idle, PhaseState, Shared
from .requests import RequestReceiver, RequestSender
from .settings import Settings

logger = logging.getLogger("xaynet.coordinator")


class StateMachine:
    """Runs phases until shutdown; single writer of all round state."""

    def __init__(self, initial: PhaseState):
        self._phase: Optional[PhaseState] = initial

    @property
    def phase(self) -> Optional[PhaseState]:
        return self._phase

    async def next(self) -> bool:
        """Runs one phase; returns False when the machine has shut down."""
        if self._phase is None:
            return False
        self._phase = await self._phase.run_phase()
        return self._phase is not None

    async def run(self) -> None:
        while await self.next():
            pass
        logger.info("state machine terminated")


class RestoreError(RuntimeError):
    """Coordinator restore failed (dangling model id, length mismatch, ...)."""


class StateMachineInitializer:
    """Builds (StateMachine, RequestSender, EventSubscriber) from settings."""

    def __init__(self, settings: Settings, store: Store, metrics=None,
                 tenant: str = "default"):
        settings.validate()
        self.settings = settings
        self.store = store
        # the tenant id this machine's round state belongs to: threads into
        # Shared (pool leases, scheduler slots, span/flight labels) and the
        # per-tenant round counters (docs/DESIGN.md §19)
        self.tenant = tenant
        # phase histograms and message counters must reach GET /metrics even
        # when no external sink is configured: default to a registry-only
        # bridge (callers may still inject any recorder, e.g. test spies)
        self.metrics = metrics if metrics is not None else BridgedMetrics()

    async def init(self) -> tuple[StateMachine, RequestSender, EventSubscriber]:
        """Fresh start (or restore when enabled and state exists)."""
        if self.settings.restore.enable:
            restored = await self._try_restore()
            if restored is not None:
                return restored
            logger.info("no coordinator state found; starting fresh")
        else:
            logger.info("restore disabled; deleting coordinator data")
            await self.store.coordinator.delete_coordinator_data()
        state = CoordinatorState.from_settings(self.settings)
        return self._assemble(state, ModelUpdate.invalidate())

    async def _try_restore(self):
        raw = await self.store.coordinator.coordinator_state()
        if raw is None:
            return None
        state = CoordinatorState.from_bytes(raw)
        logger.info("restored coordinator state at round %d", state.round_id)
        # restore the latest global model, validating its length
        # (reference: initializer.rs:199-271)
        model_update = ModelUpdate.invalidate()
        model_id = await self.store.coordinator.latest_global_model_id()
        if model_id is not None:
            blob = await self.store.models.global_model(model_id)
            if blob is None:
                raise RestoreError(
                    f"latest global model id {model_id} points to no stored model"
                )
            model = np.frombuffer(blob, dtype=np.float64)
            if model.shape[0] != state.round_params.model_length:
                raise RestoreError(
                    f"restored model length {model.shape[0]} != configured "
                    f"{state.round_params.model_length}"
                )
            model_update = ModelUpdate.new(model)
        resume = await self._try_resume_round(state)
        return self._assemble(state, model_update, initial_factory=resume)

    async def _try_resume_round(self, state: CoordinatorState):
        """Resume path for a coordinator killed MID-ROUND: when a valid
        journal entry exists for the restored round, the machine starts in
        the journaled phase (sum, update, sum2 or unmask) with the round
        state restored instead of at Idle — previously accepted messages
        survive the restart (docs/DESIGN.md §9). ``reseed=True``: the
        process died, so the store's round dictionaries are replayed from
        the journal (idempotent on durable backends) and
        accepted-but-unjournaled orphans pruned so their un-acked clients
        can retry. Returns a phase factory or None."""
        if not self.settings.resilience.checkpoint_enabled:
            return None
        ckpt = await ckpt_mod.load(self.store)
        if ckpt is None:
            return None
        try:
            reason = await ckpt_mod.validate(ckpt, state, self.store, reseed=True)
        except Exception as err:
            reason = f"validation failed: {err}"
        if reason is not None:
            logger.warning(  # lint: taint-ok: reason carries counts/names only, never key bytes
                "round journal not resumable (%s); starting at Idle", reason
            )
            ckpt_mod.RESUMES.labels(outcome="invalid").inc()
            ckpt_mod.RESUME_TOTAL.labels(phase=ckpt.phase, outcome="invalid").inc()
            return None
        ckpt_mod.RESUMES.labels(outcome="resumed").inc()
        ckpt_mod.RESUME_TOTAL.labels(phase=ckpt.phase, outcome="resumed").inc()
        logger.info(
            "resuming round %d %s phase from journal (%d models restored)",
            state.round_id,
            ckpt.phase,
            ckpt.nb_models,
        )

        def factory(shared: Shared) -> PhaseState:
            from .phases.resume import resume_phase

            shared.resume_attempts += 1  # lint: tenant-ok: budget lives on this tenant's own Shared
            return resume_phase(shared, ckpt)

        return factory

    def _assemble(
        self,
        state: CoordinatorState,
        model_update: ModelUpdate,
        initial_factory=None,
    ):
        events = EventPublisher(
            round_id=state.round_id,
            keys=state.keys,
            params=state.round_params,
            phase=PhaseName.IDLE,
            model=model_update,
        )
        request_rx = RequestReceiver(tenant=self.tenant)
        round_ctl = None
        if self.settings.liveness.adaptive:
            from .round_controller import RoundController

            round_ctl = RoundController(self.settings)
        shared = Shared(
            state=state,
            request_rx=request_rx,
            events=events,
            store=self.store,
            settings=self.settings,
            metrics=self.metrics,
            round_ctl=round_ctl,
            tenant=self.tenant,
        )
        initial = initial_factory(shared) if initial_factory is not None else Idle(shared)
        machine = StateMachine(initial)
        return machine, request_rx.sender(), events.subscribe()
