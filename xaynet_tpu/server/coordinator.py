"""Coordinator round state.

Reference: rust/xaynet-server/src/state_machine/coordinator.rs:22-134 —
round credentials + public round parameters + phase window parameters, all
derived from settings and persisted every Idle phase for checkpoint/restore.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from ..core.common import RoundParameters, RoundSeed
from ..core.crypto.encrypt import EncryptKeyPair, PublicEncryptKey, SecretEncryptKey
from .settings import Settings


@dataclass
class CoordinatorState:
    keys: EncryptKeyPair
    round_id: int
    round_params: RoundParameters

    @classmethod
    def from_settings(cls, settings: Settings) -> "CoordinatorState":
        keys = EncryptKeyPair.generate()
        mask_config = settings.mask.to_config().pair()
        return cls(
            keys=keys,
            round_id=0,
            round_params=RoundParameters(
                pk=keys.public.as_bytes(),
                sum=settings.pet.sum.prob,
                update=settings.pet.update.prob,
                seed=RoundSeed.zeroed(),
                mask_config=mask_config,
                model_length=settings.model.length,
                wire_format=2 if settings.ingest.wire_format == "packed" else 1,
            ),
        )

    def to_bytes(self) -> bytes:
        # the durable round-state blob must carry the round's secret key —
        # a restarted coordinator cannot decrypt the round's messages
        # without it; the blob lives in the coordinator's own store (§9)
        return json.dumps(  # lint: taint-ok: durable round-state blob, restore needs the round key
            {
                "public_key": self.keys.public.as_bytes().hex(),
                "secret_key": self.keys.secret.as_bytes().hex(),
                "round_id": self.round_id,
                "round_params": self.round_params.to_dict(),
            }
        ).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "CoordinatorState":
        d = json.loads(data.decode())
        return cls(
            keys=EncryptKeyPair(
                public=PublicEncryptKey(bytes.fromhex(d["public_key"])),
                secret=SecretEncryptKey(bytes.fromhex(d["secret_key"])),
            ),
            round_id=int(d["round_id"]),
            round_params=RoundParameters.from_dict(d["round_params"]),
        )
