"""The ingest pipeline: pre-filter -> admission -> shards -> workers.

Wiring order per message:

1. **pre-filter** (on the REST task, before any queue slot or crypto):
   structural length check (a ciphertext shorter than sealed-box overhead +
   message header cannot contain a PET message) and the wrong-phase gate —
   during idle/unmask/failure/shutdown NO ciphertext can be valid, so the
   message is dropped before sealed-box decryption. The tag-level phase
   filter (sum message during update, ...) still runs right after the
   sealed-box open and *before* signature verification / payload parse in
   ``services._decrypt_parse_one`` — the sealed box hides the tag, so
   pre-decrypt filtering cannot see it (docs/DESIGN.md §7).
2. **admission** — watermark verdict; shed means HTTP 429 + Retry-After.
3. **intake shard** — bounded queue, round-robin.
4. **decrypt worker** (one task per shard) — drains a batch, ONE
   thread-pool hop decrypts + verifies + task-validates all of it, then
   submits: updates through the coalescer, everything else per-message.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque

from ..core.crypto.encrypt import SEALBYTES
from ..core.message.message import HEADER_LENGTH
from ..resilience.faults import maybe_fail_async
from ..server.events import PhaseName
from ..server.requests import RequestError, RequestSender, UpdateRequest, request_from_message
from ..server.services import PetMessageHandler, ServiceError
from ..server.settings import IngestSettings
from ..telemetry import tracing as trace
from ..telemetry.registry import get_registry
from ..utils import tracing
from .admission import BATCH_SIZE_HIST, Admission, AdmissionController, Verdict
from .coalescer import UpdateCoalescer
from .intake import ShardedIntake, ShardFull

logger = logging.getLogger("xaynet.ingest")

SPAN_ADMISSION = trace.declare_span("ingest.admission")
SPAN_QUEUE_WAIT = trace.declare_span("ingest.queue_wait")
SPAN_DECRYPT_BATCH = trace.declare_span("ingest.decrypt_batch")

WORKER_RESTARTS = get_registry().counter(
    "xaynet_ingest_worker_restarts_total",
    "Ingest decrypt workers restarted by the supervisor after dying "
    "unexpectedly, by shard and tenant.",
    ("shard", "tenant"),
)

INGRESS_ACCEPTED = get_registry().counter(
    "xaynet_ingress_accepted_total",
    "Messages ACCEPTED at the ingress boundary — decrypted, verified and "
    "task-validated, then forwarded toward the state machine — by tenant. "
    "Admission ('admitted') only means a queue slot; this counts survivors "
    "of the whole intake pipeline, the coordinator-ingress headline.",
    ("tenant",),
)
INGRESS_WIRE = get_registry().counter(
    "xaynet_ingress_wire_total",
    "Accepted Update payloads by wire element layout: packed = v2 "
    "byte-planar (WIRE_PLANAR_FLAG), legacy = v1 interleaved. The mix "
    "shows how much of the fleet honors the round's negotiated format.",
    ("format",),
)

# backoff between restarts of a crash-looping worker: capped doubling, so a
# deterministic crash (bad build) cannot busy-spin the event loop
_RESTART_BACKOFF_BASE_S = 0.05
_RESTART_BACKOFF_MAX_S = 5.0


class RateWindow:
    """Per-second event buckets over a short sliding window: the
    accepted/shed *rates* for the /healthz + /statusz ingress section,
    without scraping a metrics backend. All calls run on the event loop
    (submit and the decrypt workers are both loop tasks), so no lock."""

    def __init__(self, window_s: int = 10):
        if window_s < 1:
            raise ValueError("window must be >= 1s")
        self.window_s = window_s
        self._buckets: deque[tuple[int, int]] = deque()

    def add(self, n: int = 1, now: float | None = None) -> None:
        t = int(time.monotonic() if now is None else now)
        if self._buckets and self._buckets[-1][0] == t:
            self._buckets[-1] = (t, self._buckets[-1][1] + n)
        else:
            self._buckets.append((t, n))
        self._trim(t)

    def rate(self, now: float | None = None) -> float:
        """Events/s averaged over the window (the current partial second
        included — a steady source reads steady, a stopped one decays to
        zero within ``window_s``)."""
        t = int(time.monotonic() if now is None else now)
        self._trim(t)
        return sum(c for _, c in self._buckets) / float(self.window_s)

    def _trim(self, t: int) -> None:
        cutoff = t - self.window_s
        while self._buckets and self._buckets[0][0] <= cutoff:
            self._buckets.popleft()

# phases whose tag can appear in a valid ciphertext; anything else is shed
# before we even pay for the sealed-box open
_INGESTIBLE = {PhaseName.SUM, PhaseName.UPDATE, PhaseName.SUM2}

_MIN_CIPHERTEXT = SEALBYTES + HEADER_LENGTH


class IngestPipeline:
    """Admission-controlled, batched path from REST to the state machine."""

    def __init__(
        self,
        handler: PetMessageHandler,
        request_tx: RequestSender,
        events,
        settings: IngestSettings,
        tenant: str = "default",
        budget=None,
    ):
        settings.validate()
        self.handler = handler
        self.request_tx = request_tx
        self.events = events
        self.settings = settings
        # multi-tenant seam (docs/DESIGN.md §19): the tenant id labels this
        # pipeline's logs/metrics; `budget` (tenancy.TenantAdmissionBudget)
        # layers the per-tenant share of the PROCESS-wide intake on top of
        # this pipeline's own AdmissionController — a flooding tenant sheds
        # before it can crowd other tenants' decrypt capacity
        self.tenant = tenant
        self.budget = budget
        self.intake = ShardedIntake(settings.shards, settings.queue_bound)
        self.admission = AdmissionController(
            capacity=self.intake.capacity,
            high_watermark=settings.high_watermark,
            low_watermark=settings.low_watermark,
            retry_after_seconds=settings.retry_after_seconds,
        )
        self.coalescer = (
            UpdateCoalescer(
                request_tx,
                max_batch=settings.coalesce_max_batch,
                linger_s=settings.coalesce_linger_ms / 1000.0,
            )
            if settings.coalesce
            else None
        )
        self._workers: list[asyncio.Task] = []  # guarded-by: event-loop
        # ingress accounting (guarded-by: event-loop — submit and the
        # decrypt workers are all loop tasks): totals + short-window rates
        # + the accepted wire-format mix, surfaced as the "ingress" section
        # of /healthz and /statusz
        self._accepted = 0
        self._shed = 0
        self._rejected = 0
        self._wire_mix = {"packed": 0, "legacy": 0}
        self._accepted_rate = RateWindow()
        self._shed_rate = RateWindow()
        self._ingress_accepted = INGRESS_ACCEPTED.labels(tenant=tenant)

    # --- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        if self._workers:
            return
        self._workers = [
            asyncio.create_task(
                self._supervise(shard), name=f"ingest-worker-{shard.index}"
            )
            for shard in self.intake.shards
        ]
        logger.info(
            "ingest pipeline up: %d shards x %d bound, decrypt batch <= %d, coalesce %s",
            self.settings.shards,
            self.settings.queue_bound,
            self.settings.max_batch,
            f"<= {self.settings.coalesce_max_batch}" if self.coalescer else "off",
        )

    async def stop(self) -> None:
        for task in self._workers:
            task.cancel()
        for task in self._workers:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._workers = []
        if self.coalescer is not None:
            await self.coalescer.close()
        if self.budget is not None:
            # return this tenant's entire held share: messages still queued
            # in the intake die with this pipeline, and a stopped tenant
            # must not keep budget charged against the OTHER tenants'
            # process-wide capacity (docs/DESIGN.md §19)
            self.budget.discharge(self.tenant, self.budget.held(self.tenant))

    @property
    def running(self) -> bool:
        return bool(self._workers)

    # --- intake -----------------------------------------------------------

    def _phase(self) -> PhaseName:
        return self.events.phase.get_latest().event

    async def submit(self, encrypted: bytes) -> Admission:
        """Admit, shed, or drop one encrypted message (REST entry point).

        The REST request id is assigned HERE and rides with the ciphertext
        through the intake queue, so the decrypt worker and the coalescer
        log under the same id the request logs carry — the id no longer
        dies at the pipeline boundary.
        """
        if len(encrypted) < _MIN_CIPHERTEXT or self._phase() not in _INGESTIBLE:
            # cheap pre-decrypt rejection: structurally impossible, or no
            # phase is accepting messages at all
            return self.admission.dropped("pre-filter")
        request_id = tracing.new_request_id()
        with trace.get_tracer().span(
            SPAN_ADMISSION, rid=request_id, tenant=self.tenant
        ) as span:
            if self.budget is not None and not self.budget.charge(self.tenant):
                # per-tenant budget exceeded: shed BEFORE the shared
                # controller — this tenant is over its share even if the
                # process as a whole has headroom
                span.set(verdict="shed-budget")
                self._count_shed()
                return Admission(
                    Verdict.SHED,
                    retry_after=self.admission.retry_after(self.intake.occupancy),
                )
            verdict = self.admission.admit(self.intake.occupancy)
            if verdict.shed:
                if self.budget is not None:
                    self.budget.discharge(self.tenant)
                span.set(verdict="shed")
                self._count_shed()
                return verdict
            try:
                self.intake.put_nowait((request_id, time.monotonic(), encrypted))
            except ShardFull:
                if self.budget is not None:
                    self.budget.discharge(self.tenant)
                span.set(verdict="shed-shard-full")
                self._count_shed()
                return self.admission.shed_shard_full(self.intake.occupancy)
            self.admission.count_admitted()
            span.set(verdict="admitted")
        return verdict

    # --- drain ------------------------------------------------------------

    async def _supervise(self, shard) -> None:
        """Keep the shard's decrypt worker alive: a worker that dies on an
        unexpected error (not a single poisoned batch — those are absorbed
        inside ``_worker``) is restarted with capped-doubling backoff, so
        one crash never silently halves the coordinator's intake capacity
        for the rest of the process."""
        backoff = _RESTART_BACKOFF_BASE_S
        while True:
            try:
                await self._worker(shard)
                return  # _worker only returns on cancellation paths
            except asyncio.CancelledError:
                raise
            except Exception:
                WORKER_RESTARTS.labels(shard=str(shard.index), tenant=self.tenant).inc()
                logger.exception(
                    "ingest worker %d died; restarting in %.2fs", shard.index, backoff
                )
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, _RESTART_BACKOFF_MAX_S)

    async def _worker(self, shard) -> None:
        while True:
            # deterministic chaos: a fault plan can kill this worker here
            # (before any message is claimed, so nothing in flight is lost);
            # the supervisor restarts it
            await maybe_fail_async(f"ingest.worker.{shard.index}")
            batch = await shard.get_batch(
                self.settings.max_batch, self.settings.linger_ms / 1000.0
            )
            self.intake.drained()
            if self.budget is not None:
                # the drained messages leave this tenant's share of the
                # process-wide budget the moment they leave the queue
                self.budget.discharge(self.tenant, len(batch))
            self.admission.observe(self.intake.occupancy)
            BATCH_SIZE_HIST.labels(stage="decrypt").observe(len(batch))
            # the oldest member's wait IS the batch's queue-wait span: it
            # bounds every other member's and is the number backpressure
            # tuning needs
            oldest = min(ts for _, ts, _ in batch)
            trace.get_tracer().record_span(
                SPAN_QUEUE_WAIT,
                start=oldest,
                duration=time.monotonic() - oldest,
                shard=shard.index,
                n=len(batch),
            )
            try:
                await self._process(batch, shard.index)
            except asyncio.CancelledError:
                raise
            except Exception:
                # a poisoned batch must not kill the shard's worker
                logger.exception(
                    "ingest worker %d: batch failed (rids: %s)",
                    shard.index,
                    " ".join(rid for rid, _, _ in batch),
                )

    async def _process(self, batch: list[tuple], shard_index: int = -1) -> None:
        with trace.get_tracer().span(
            SPAN_DECRYPT_BATCH, shard=shard_index, n=len(batch)
        ) as span:
            results = await self.handler.process_batch([raw for _, _, raw in batch])
            rejected = 0
            submits = []
            coalescing = self.coalescer is not None and self._phase() is PhaseName.UPDATE
            for (request_id, _, _), res in zip(batch, results):
                if res is None:
                    continue  # multipart chunk absorbed
                if isinstance(res, ServiceError):
                    self.admission.count_rejection(res.stage)
                    self._rejected += 1
                    rejected += 1
                    logger.debug(
                        "[%s] ingest worker %d: message dropped at %s: %s",
                        request_id,
                        shard_index,
                        res.stage,
                        res,
                    )
                    continue
                self._count_accepted(res)
                req = request_from_message(res)
                if coalescing and isinstance(req, UpdateRequest):
                    with tracing.use_request_id(request_id):
                        await self.coalescer.add(req)  # captures the current id
                else:
                    submits.append(self._submit_one(req, request_id))
            span.set(rejected=rejected)
        if submits:
            await asyncio.gather(*submits)
        if self.coalescer is not None and self.coalescer.pending:
            # don't leave a partial micro-batch lingering when the shard
            # queue is empty anyway — latency buys nothing here
            if self.intake.occupancy == 0:
                await self.coalescer.flush()

    async def _submit_one(self, req, request_id: str) -> None:
        # the coroutine runs later under gather, so the message's tracing id
        # must be re-entered here — reading the ambient contextvar would
        # stamp every envelope of the batch with the LAST message's id
        try:
            with tracing.use_request_id(request_id):
                await self.request_tx.request(req)
        except RequestError:
            self.admission.count_rejection("state-machine")

    # --- ingress accounting ----------------------------------------------

    def _count_shed(self) -> None:
        self._shed += 1
        self._shed_rate.add()

    def _count_accepted(self, message) -> None:
        """One message survived the whole intake pipeline. Update payloads
        also book their wire element layout (the packed-vs-legacy mix)."""
        self._accepted += 1
        self._accepted_rate.add()
        self._ingress_accepted.inc()
        payload = getattr(message, "payload", None)
        wire_planar = getattr(payload, "wire_planar", None)
        if wire_planar is not None:
            fmt = "packed" if wire_planar else "legacy"
            self._wire_mix[fmt] += 1
            INGRESS_WIRE.labels(format=fmt).inc()

    def ingress_stats(self) -> dict:
        """The ``ingress`` section of /healthz and /statusz: end-to-end
        acceptance (not mere admission), shed pressure, per-shard intake
        occupancy, and the accepted wire-format mix."""
        return {
            "accepted_total": self._accepted,
            "accepted_per_s": round(self._accepted_rate.rate(), 2),
            "shed_total": self._shed,
            "shed_per_s": round(self._shed_rate.rate(), 2),
            "rejected_total": self._rejected,
            "shard_occupancy": [s.occupancy for s in self.intake.shards],
            "wire": dict(self._wire_mix),
        }

    # --- health -----------------------------------------------------------

    def health(self) -> dict:
        """Saturation snapshot for GET /healthz."""
        occupancy = self.intake.occupancy
        self.admission.observe(occupancy)
        out = {
            "saturated": self.admission.saturated,
            "occupancy": occupancy,
            "capacity": self.intake.capacity,
            "shards": len(self.intake.shards),
            "running": self.running,
            # updates buffered toward the next coalesced envelope (operators
            # watching an edge's backlog need the pre-seal depth too)
            "coalescer_pending": self.coalescer.pending if self.coalescer else 0,
            "ingress": self.ingress_stats(),
        }
        if self.budget is not None:
            out["tenant"] = self.tenant
            out["budget_held"] = self.budget.held(self.tenant)
            out["budget_limit"] = self.budget.per_tenant
        return out
