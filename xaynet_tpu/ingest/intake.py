"""Bounded sharded intake queues with batched draining.

Each shard is a hard-bounded ``asyncio.Queue`` drained by its own decrypt
worker; arrivals spread round-robin so no single queue serializes the
fan-in. ``get_batch`` implements the linger discipline: take what is
immediately available, wait at most ``linger_s`` for the batch to fill,
never return empty — the worker amortizes one thread-pool hop over the
whole batch.
"""

from __future__ import annotations

import asyncio
import itertools

from ..telemetry.registry import get_registry

_OCCUPANCY = get_registry().histogram(
    "xaynet_ingest_shard_occupancy",
    "Shard queue depth observed at each enqueue (per shard).",
    ("shard",),
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096),
)
_OCCUPANCY_NOW = get_registry().gauge(
    "xaynet_ingest_occupancy",
    "Messages currently queued across all intake shards.",
)


class ShardFull(Exception):
    """The shard's hard bound rejected the put."""


class IntakeShard:
    """One bounded intake queue.

    ``max_occupancy`` records the high-water mark ever observed — the
    integration tests assert it never exceeds the configured bound.
    """

    def __init__(self, index: int, bound: int):
        if bound < 1:
            raise ValueError("shard bound must be >= 1")
        self.index = index
        self.bound = bound
        self._queue: asyncio.Queue[bytes] = asyncio.Queue(maxsize=bound)
        self.max_occupancy = 0  # guarded-by: event-loop
        self._hist = _OCCUPANCY.labels(shard=str(index))

    @property
    def occupancy(self) -> int:
        return self._queue.qsize()

    def put_nowait(self, item: bytes) -> None:
        try:
            self._queue.put_nowait(item)
        except asyncio.QueueFull:
            raise ShardFull(f"shard {self.index} at bound {self.bound}") from None
        depth = self._queue.qsize()
        self.max_occupancy = max(self.max_occupancy, depth)
        self._hist.observe(depth)

    async def get_batch(self, max_batch: int, linger_s: float) -> list[bytes]:
        """At least one item; up to ``max_batch``, lingering ``linger_s``."""
        batch = [await self._queue.get()]
        loop = asyncio.get_running_loop()
        deadline = loop.time() + linger_s
        while len(batch) < max_batch:
            try:
                batch.append(self._queue.get_nowait())
                continue
            except asyncio.QueueEmpty:
                pass
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                batch.append(await asyncio.wait_for(self._queue.get(), remaining))
            except asyncio.TimeoutError:
                break
        return batch


class ShardedIntake:
    """Round-robin fan-out over ``n`` bounded shards."""

    def __init__(self, shards: int, bound_per_shard: int):
        if shards < 1:
            raise ValueError("need at least one shard")
        self.shards = [IntakeShard(i, bound_per_shard) for i in range(shards)]
        self.capacity = shards * bound_per_shard
        self._rr = itertools.cycle(range(shards))  # guarded-by: event-loop

    @property
    def occupancy(self) -> int:
        return sum(s.occupancy for s in self.shards)

    @property
    def max_occupancy(self) -> int:
        return max(s.max_occupancy for s in self.shards)

    def put_nowait(self, item: bytes) -> None:
        """Enqueue on the next shard with room (starting round-robin).

        Raises ``ShardFull`` only when EVERY shard is at its bound.
        """
        start = next(self._rr)
        n = len(self.shards)
        for off in range(n):
            shard = self.shards[(start + off) % n]
            try:
                shard.put_nowait(item)
                _OCCUPANCY_NOW.set(self.occupancy)
                return
            except ShardFull:
                continue
        raise ShardFull("all intake shards at bound")

    def drained(self) -> None:
        """Refresh the occupancy gauge after a worker drained a batch."""
        _OCCUPANCY_NOW.set(self.occupancy)
