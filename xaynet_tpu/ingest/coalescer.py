"""Update coalescing: many verified updates, one channel envelope.

During the update phase each accepted message costs a channel envelope, a
phase wakeup, and eventually a fold dispatch. The coalescer buffers
verified ``UpdateRequest``s for up to ``max_batch`` messages or
``linger_s`` seconds and submits them as ONE ``CoalescedUpdates`` envelope;
the update phase batch-prevalidates the members (one device round-trip for
the group when wire ingest is on), processes them in order (validation +
seed-dict insert stay per-member, so the seed-dict/masked-model pairing is
never reordered) and SUBMITS the micro-batch into the streaming
aggregation pipeline as a single stacked ``masked_add`` dispatch — the
fold of batch N overlaps the decrypt/validate/stage of batch N+1, and the
pipeline drains at the phase transition. During sum/sum2 the pipeline
bypasses the coalescer entirely — those requests are per-message by
construction.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ..server.requests import CoalescedUpdates, RequestError, RequestSender, UpdateRequest
from ..telemetry import tracing as trace
from ..utils import tracing
from .admission import BATCH_SIZE_HIST, AdmissionController

logger = logging.getLogger("xaynet.ingest")

SPAN_COALESCE = trace.declare_span("ingest.coalesce")


class UpdateCoalescer:
    """Micro-batches ``UpdateRequest``s into ``CoalescedUpdates`` envelopes."""

    def __init__(self, request_tx: RequestSender, max_batch: int = 32, linger_s: float = 0.002):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.request_tx = request_tx
        self.max_batch = max_batch
        self.linger_s = linger_s
        self._buf: list[tuple[UpdateRequest, asyncio.Future, str]] = []  # guarded-by: event-loop
        self._opened: float = 0.0  # first add of the current buffer  # guarded-by: event-loop
        self._linger_task: Optional[asyncio.Task] = None  # guarded-by: event-loop
        self.batches_sent = 0  # guarded-by: event-loop
        self.members_sent = 0  # guarded-by: event-loop

    @property
    def pending(self) -> int:
        return len(self._buf)

    async def add(self, req: UpdateRequest) -> asyncio.Future:
        """Buffer one verified update; returns its member future.

        The caller need not await the future — member rejections are
        consumed and counted here so an abandoned future never warns.
        """
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        fut.add_done_callback(_consume_member_result)
        if not self._buf:
            self._opened = time.monotonic()
        self._buf.append((req, fut, tracing.current_request_id()))
        if len(self._buf) >= self.max_batch:
            await self.flush()
        elif self._linger_task is None:
            self._linger_task = asyncio.create_task(self._linger_flush())
        return fut

    async def _linger_flush(self) -> None:
        await asyncio.sleep(self.linger_s)
        self._linger_task = None
        await self.flush()

    async def flush(self) -> None:
        """Submit the buffered micro-batch as one envelope (no-op if empty).

        Blocks until the state machine has handled the whole batch — the
        ingest worker behind ``add`` therefore backpressures naturally.
        """
        if self._linger_task is not None:
            self._linger_task.cancel()
            self._linger_task = None
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        batch = CoalescedUpdates(
            members=[req for req, _, _ in buf],
            responses=[fut for _, fut, _ in buf],
            request_ids=[rid for _, _, rid in buf],
        )
        BATCH_SIZE_HIST.labels(stage="coalesce").observe(len(batch))
        self.batches_sent += 1
        self.members_sent += len(batch)
        # the coalesce window as a retroactive span (first add -> submit),
        # plus the member ids in the log so one grep joins a request's REST
        # log line to the envelope that carried it into the state machine
        trace.get_tracer().record_span(
            SPAN_COALESCE,
            start=self._opened,
            duration=time.monotonic() - self._opened,
            n=len(batch),
        )
        logger.debug(
            "coalesced %d updates into one envelope (rids: %s)",
            len(batch),
            " ".join(rid for _, _, rid in buf),
        )
        try:
            await self.request_tx.request(batch)
        except RequestError as err:
            # batch-level rejection (purge at phase end, shutdown): members
            # that the phase never reached inherit the batch verdict
            batch.reject_members(err)

    async def close(self) -> None:
        await self.flush()


def _consume_member_result(fut: asyncio.Future) -> None:
    if fut.cancelled():
        return
    err = fut.exception()
    if err is not None:
        AdmissionController.count_rejection("state-machine")
