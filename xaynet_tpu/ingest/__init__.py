"""Admission-controlled batched ingest pipeline.

The fan-in between the REST surface and the state machine (NET-SA shows
secure-aggregation throughput is dominated by exactly this path):

    POST /message -> pre-filter -> AdmissionController -> ShardedIntake
        -> DecryptWorker (batched sealed-box open + verify, one thread-pool
           hop per batch) -> UpdateCoalescer (micro-batched UpdateRequests,
           one stacked fold dispatch per batch) -> state machine

Every queue is bounded; saturation sheds load at the door (HTTP 429 +
Retry-After) instead of growing coordinator memory.
"""

from .admission import AdmissionController, Verdict
from .coalescer import UpdateCoalescer
from .intake import IntakeShard, ShardedIntake
from .pipeline import IngestPipeline

__all__ = [
    "AdmissionController",
    "IngestPipeline",
    "IntakeShard",
    "ShardedIntake",
    "UpdateCoalescer",
    "Verdict",
]
