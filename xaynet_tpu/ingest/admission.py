"""Admission control: watermark hysteresis, shedding, and its telemetry.

The controller is the single authority on whether the coordinator accepts
one more encrypted message. It tracks total intake occupancy against a
high/low watermark pair (fractions of total capacity): crossing the high
watermark flips the pipeline into a *saturated* state where every new
arrival is shed (HTTP 429 + Retry-After upstream); the state clears only
once drain brings occupancy back under the low watermark — hysteresis, so
a loaded coordinator sheds in contiguous windows instead of flapping
per-message.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from enum import Enum

from ..telemetry.registry import get_registry

_ADMITTED = get_registry().counter(
    "xaynet_ingest_admitted_total",
    "Messages admitted into the intake shards.",
)
_SHED = get_registry().counter(
    "xaynet_ingest_shed_total",
    "Messages shed by admission control (intake saturated or shard full).",
)
_REJECTED = get_registry().counter(
    "xaynet_ingest_rejected_total",
    "Messages dropped by the ingest pipeline, by stage (pre-filter = cheap "
    "checks before decryption; decrypt/parse/phase-filter/task-validator = "
    "pipeline stages; state-machine = protocol rejection).",
    ("stage",),
)
_SATURATED = get_registry().gauge(
    "xaynet_ingest_saturated",
    "1 while admission control is shedding (watermark hysteresis), else 0.",
)
BATCH_SIZE_HIST = get_registry().histogram(
    "xaynet_ingest_batch_size",
    "Messages per ingest batch, by stage (decrypt = one thread-pool hop; "
    "coalesce = one state-machine envelope / stacked fold dispatch).",
    ("stage",),
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
)


class Verdict(Enum):
    ADMITTED = "admitted"
    SHED = "shed"
    DROPPED = "dropped"  # pre-filter rejection (REST still answers 200)


@dataclass
class Admission:
    """What the REST layer needs to answer one POST /message."""

    verdict: Verdict
    retry_after: float = 0.0

    @property
    def shed(self) -> bool:
        return self.verdict is Verdict.SHED


class AdmissionController:
    """Watermark-based load shedding over a fixed total capacity."""

    def __init__(
        self,
        capacity: int,
        high_watermark: float = 0.8,
        low_watermark: float = 0.5,
        retry_after_seconds: float = 1.0,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if not (0.0 < low_watermark <= high_watermark <= 1.0):
            raise ValueError("watermarks must satisfy 0 < low <= high <= 1")
        self.capacity = capacity
        # ceil: a high watermark of 1.0 must mean "full", never capacity+1
        self.high_mark = min(capacity, math.ceil(high_watermark * capacity))
        self.low_mark = math.floor(low_watermark * capacity)
        self.retry_after_seconds = retry_after_seconds
        self._saturated = False  # guarded-by: event-loop
        _SATURATED.set(0)

    @property
    def saturated(self) -> bool:
        return self._saturated

    def observe(self, occupancy: int) -> None:
        """Update the hysteresis state from current total occupancy (called
        on both enqueue and drain so recovery needs no new arrivals)."""
        if self._saturated:
            if occupancy <= self.low_mark:
                self._saturated = False
                _SATURATED.set(0)
        elif occupancy >= self.high_mark:
            self._saturated = True
            _SATURATED.set(1)

    def admit(self, occupancy: int) -> Admission:
        """Admission verdict for one arrival given current total occupancy.

        ``occupancy >= capacity`` needs no separate check: ``high_mark <=
        capacity``, so ``observe`` has already flipped the saturated state.
        The admitted counter is incremented by the caller once the message
        actually lands in a shard (``count_admitted``), so a full-shard
        fallback shed can never double-count.
        """
        self.observe(occupancy)
        if self._saturated:
            _SHED.inc()
            return Admission(Verdict.SHED, retry_after=self.retry_after(occupancy))
        return Admission(Verdict.ADMITTED)

    def shed_shard_full(self, occupancy: int) -> Admission:
        """A shard's hard bound rejected the put (capacity race)."""
        _SHED.inc()
        return Admission(Verdict.SHED, retry_after=self.retry_after(occupancy))

    @staticmethod
    def count_admitted() -> None:
        """Count one message that actually landed in an intake shard."""
        _ADMITTED.inc()

    def retry_after(self, occupancy: int) -> float:
        """Back-off hint: the configured floor, scaled up with overload depth
        so deeply saturated intakes spread the retry storm out further."""
        overload = max(0.0, occupancy - self.low_mark) / max(1, self.capacity)
        return self.retry_after_seconds * (1.0 + 3.0 * overload)

    @staticmethod
    def dropped(stage: str) -> Admission:
        """Count a pre-admission drop (cheap pre-filter rejection)."""
        _REJECTED.labels(stage=stage).inc()
        return Admission(Verdict.DROPPED)

    @staticmethod
    def count_rejection(stage: str) -> None:
        """Count a post-admission drop (decrypt/parse/state-machine...)."""
        _REJECTED.labels(stage=stage).inc()
