"""Coordinator clients: in-process (simulation/tests) and HTTP.

Reference surface: rust/xaynet-sdk/src/client.rs:59-213 (five endpoints:
params / sums / seeds / model / message). The in-process client talks
directly to a coordinator's fetcher and message handler — the reference
proves the whole protocol is testable without a network
(SURVEY §4: in-process multi-node).
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

import numpy as np

from ..core.common import RoundParameters, UpdateSeedDict
from .traits import XaynetClient


class InProcessClient(XaynetClient):
    """Direct wiring to an in-process coordinator (no sockets)."""

    def __init__(self, fetcher, message_handler):
        self.fetcher = fetcher
        self.handler = message_handler

    async def get_round_params(self) -> RoundParameters:
        return self.fetcher.round_params()

    async def get_sums(self) -> Optional[dict]:
        return self.fetcher.sum_dict()

    async def get_seeds(self, pk: bytes) -> Optional[UpdateSeedDict]:
        return self.fetcher.seeds_for(pk)

    async def get_model(self) -> Optional[np.ndarray]:
        return self.fetcher.model()

    async def send_message(self, encrypted: bytes) -> None:
        """Mirrors the REST semantics: drops/rejections are swallowed
        (POST /message answers 200 regardless; clients learn outcomes from
        round progression)."""
        from ..server.requests import RequestError
        from ..server.services import ServiceError

        try:
            await self.handler.handle_message(encrypted)
        except (ServiceError, RequestError):
            pass


class HttpClient(XaynetClient):
    """HTTP client for a remote coordinator (REST API, rest.py).

    Uses asyncio streams directly — no third-party HTTP dependency.
    """

    def __init__(self, base_url: str, timeout: float = 30.0, tls_context=None):
        self.tls = tls_context
        if base_url.startswith("https://"):
            base_url = base_url[len("https://") :]
            if self.tls is None:
                import ssl

                self.tls = ssl.create_default_context()
        elif base_url.startswith("http://"):
            base_url = base_url[len("http://") :]
        self.host, _, port = base_url.partition(":")
        self.port = int(port or (443 if self.tls is not None else 80))
        self.timeout = timeout

    async def _request(
        self, method: str, path: str, body: bytes | None = None
    ) -> tuple[int, bytes]:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=self.tls), self.timeout
        )
        try:
            head = (
                f"{method} {path} HTTP/1.1\r\nHost: {self.host}\r\n"
                f"Content-Length: {len(body) if body else 0}\r\n"
                "Connection: close\r\n\r\n"
            ).encode()
            writer.write(head + (body or b""))
            await writer.drain()
            status_line = await asyncio.wait_for(reader.readline(), self.timeout)
            status = int(status_line.split()[1])
            content_length = 0
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b""):
                    break
                name, _, value = line.decode().partition(":")
                if name.strip().lower() == "content-length":
                    content_length = int(value.strip())
            payload = await reader.readexactly(content_length) if content_length else b""
            return status, payload
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def get_round_params(self) -> RoundParameters:
        status, body = await self._request("GET", "/params")
        if status != 200:
            raise RuntimeError(f"GET /params -> {status}")
        return RoundParameters.from_dict(json.loads(body.decode()))

    async def get_sums(self) -> Optional[dict]:
        status, body = await self._request("GET", "/sums")
        if status == 204:
            return None
        if status != 200:
            raise RuntimeError(f"GET /sums -> {status}")
        raw = json.loads(body.decode())
        return {bytes.fromhex(k): bytes.fromhex(v) for k, v in raw.items()}

    async def get_seeds(self, pk: bytes) -> Optional[UpdateSeedDict]:
        from ..core.mask.seed import EncryptedMaskSeed

        status, body = await self._request("GET", f"/seeds?pk={pk.hex()}")
        if status == 204:
            return None
        if status != 200:
            raise RuntimeError(f"GET /seeds -> {status}")
        raw = json.loads(body.decode())
        return {bytes.fromhex(k): EncryptedMaskSeed(bytes.fromhex(v)) for k, v in raw.items()}

    async def get_model(self) -> Optional[np.ndarray]:
        status, body = await self._request("GET", "/model")
        if status == 204:
            return None
        if status != 200:
            raise RuntimeError(f"GET /model -> {status}")
        return np.frombuffer(body, dtype=np.float64)

    async def send_message(self, encrypted: bytes) -> None:
        status, body = await self._request("POST", "/message", encrypted)
        if status != 200:
            raise RuntimeError(f"POST /message -> {status}: {body[:200]!r}")
