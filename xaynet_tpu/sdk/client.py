"""Coordinator clients: in-process (simulation/tests), HTTP, and the
retrying :class:`ResilientClient` wrapper.

Reference surface: rust/xaynet-sdk/src/client.rs:59-213 (five endpoints:
params / sums / seeds / model / message). The in-process client talks
directly to a coordinator's fetcher and message handler — the reference
proves the whole protocol is testable without a network
(SURVEY §4: in-process multi-node).

Error taxonomy (docs/DESIGN.md §10): every HTTP failure surfaces as a
typed :class:`ClientError` instead of a bare ``RuntimeError`` —
``ClientShedError`` for a 429 from the admission controller (carrying the
server's ``Retry-After``), ``ClientTransientError`` for connection-level
faults and retryable statuses, ``ClientPermanentError`` for everything a
retry cannot fix — so the retry wrapper and the participant state machine
classify without string-matching. ``ResilientClient`` wraps any
``XaynetClient`` with the resilience layer's decorrelated-jitter
``RetryPolicy``, honoring ``Retry-After`` as a backoff floor.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

import numpy as np

from ..core.common import RoundParameters, UpdateSeedDict
from ..resilience.policy import RetryPolicy
from ..telemetry import tracing as trace
from ..telemetry.registry import get_registry
from .traits import XaynetClient

logger = logging.getLogger("xaynet.participant")

_registry = get_registry()
CLIENT_DROPS = _registry.counter(
    "xaynet_sdk_client_injected_drops_total",
    "SDK sends silently dropped by the installed fault plan (sdk.drop).",
)

# one span name per endpoint (closed set — the DESIGN §16 table row), plus
# the per-attempt child span the retry loop emits
SPAN_PARAMS = trace.declare_span("sdk.params")
SPAN_SUMS = trace.declare_span("sdk.sums")
SPAN_SEEDS = trace.declare_span("sdk.seeds")
SPAN_MODEL = trace.declare_span("sdk.model")
SPAN_SEND = trace.declare_span("sdk.send")
SPAN_ATTEMPT = trace.declare_span("sdk.attempt")
_ENDPOINT_SPANS = {
    "params": SPAN_PARAMS,
    "sums": SPAN_SUMS,
    "seeds": SPAN_SEEDS,
    "model": SPAN_MODEL,
    "send": SPAN_SEND,
}


class ClientError(Exception):
    """A coordinator call failed; ``transient`` drives retry decisions."""

    transient = False

    def __init__(self, message: str, status: Optional[int] = None,
                 retry_after: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.retry_after = retry_after


class ClientPermanentError(ClientError):
    """Retrying cannot help (4xx protocol errors, malformed responses)."""


class ClientTransientError(ClientError):
    """Worth retrying in place: connection faults, timeouts, 5xx."""

    transient = True


class ClientShedError(ClientTransientError):
    """HTTP 429 from the admission controller; ``retry_after`` is the
    server-requested backoff floor in seconds."""


# non-5xx statuses a retry can fix: request timeout and too-early
_TRANSIENT_STATUSES = frozenset({408, 425})


def classify_status(
    status: int, retry_after: Optional[float], context: str
) -> ClientError:
    """Map an HTTP error status onto the typed hierarchy: any 5xx is
    transient except 501 Not Implemented (that never heals) — proxies in
    front of a coordinator emit plenty beyond the 502/503/504 gateway
    family (507, 520-529, ...), and all of them mean "try again"."""
    message = f"{context} -> {status}"
    if status == 429:
        return ClientShedError(message, status=status, retry_after=retry_after)
    if status in _TRANSIENT_STATUSES or (500 <= status < 600 and status != 501):
        return ClientTransientError(message, status=status, retry_after=retry_after)
    return ClientPermanentError(message, status=status)


class InProcessClient(XaynetClient):
    """Direct wiring to an in-process coordinator (no sockets)."""

    def __init__(self, fetcher, message_handler):
        self.fetcher = fetcher
        self.handler = message_handler

    async def get_round_params(self) -> RoundParameters:
        return self.fetcher.round_params()

    async def get_sums(self) -> Optional[dict]:
        return self.fetcher.sum_dict()

    async def get_seeds(self, pk: bytes) -> Optional[UpdateSeedDict]:
        return self.fetcher.seeds_for(pk)

    async def get_model(self) -> Optional[np.ndarray]:
        return self.fetcher.model()

    async def send_message(self, encrypted: bytes) -> None:
        """Mirrors the REST semantics: drops/rejections are swallowed
        (POST /message answers 200 regardless; clients learn outcomes from
        round progression)."""
        from ..server.requests import RequestError
        from ..server.services import ServiceError

        try:
            await self.handler.handle_message(encrypted)
        except (ServiceError, RequestError):
            pass


class HttpClient(XaynetClient):
    """HTTP client for a remote coordinator (REST API, rest.py).

    Uses asyncio streams directly — no third-party HTTP dependency. This
    is the transport the resilient wrapper sits on; deployments should
    construct ``ResilientClient(HttpClient(url))`` (what ``Participant``
    does for URL arguments).
    """

    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        tls_context=None,
        keep_alive: bool = True,
        max_idle: int = 4,
    ):
        self.tls = tls_context
        if base_url.startswith("https://"):
            base_url = base_url[len("https://") :]
            if self.tls is None:
                import ssl

                self.tls = ssl.create_default_context()
        elif base_url.startswith("http://"):
            base_url = base_url[len("http://") :]
        # a path suffix scopes every request (multi-tenant coordinators
        # serve per-tenant routes under /t/<tenant>/..., docs/DESIGN.md
        # §19): "host:port/t/a" prefixes "/t/a" onto each request path
        base_url, _, prefix = base_url.partition("/")
        self.path_prefix = f"/{prefix.rstrip('/')}" if prefix else ""
        self.host, _, port = base_url.partition(":")
        self.port = int(port or (443 if self.tls is not None else 80))
        self.timeout = timeout
        # transport keep-alive: reuse one connection per host instead of
        # re-handshaking per request (ROADMAP item 5's transport tax). The
        # idle pool holds a handful of connections so concurrent callers
        # sharing this client each reuse their own instead of serializing;
        # ``keep_alive=False`` restores the historical one-shot behavior.
        self.keep_alive = keep_alive
        self.max_idle = max(1, max_idle)
        self._idle: list[tuple] = []  # (reader, writer, owning loop)
        self.connections_opened = 0  # reuse observability (tests/metrics)

    def close(self) -> None:
        """Drop every idle connection (best-effort; safe cross-loop)."""
        idle, self._idle = self._idle, []
        for _, writer, _ in idle:
            try:
                writer.close()
            except Exception:
                pass

    async def _connect(self):
        try:
            reader, writer = await asyncio.wait_for(
                # the SDK's one raw socket: this IS the wrapped transport
                asyncio.open_connection(  # lint: raw-http-ok
                    self.host, self.port, ssl=self.tls
                ),
                self.timeout,
            )
        except (OSError, asyncio.TimeoutError) as err:
            raise ClientTransientError(f"connect failed: {err}") from err
        self.connections_opened += 1
        return reader, writer

    def _checkout(self):
        """Pop an idle connection usable on the CURRENT loop (connections
        are loop-bound; callers like the soak driver run one ``asyncio.run``
        per request, so a cached stream from a dead loop must be skipped)."""
        loop = asyncio.get_running_loop()
        while self._idle:
            reader, writer, owner = self._idle.pop()
            if owner is loop and not writer.is_closing():
                return reader, writer
            try:
                writer.close()
            except Exception:
                pass
        return None

    def _checkin(self, reader, writer, reusable: bool) -> None:
        if (
            self.keep_alive
            and reusable
            and len(self._idle) < self.max_idle
            and not writer.is_closing()
        ):
            self._idle.append((reader, writer, asyncio.get_running_loop()))
            return
        writer.close()

    async def _request(
        self,
        method: str,
        path: str,
        body: bytes | None = None,
        headers: Optional[dict] = None,
    ) -> tuple[int, dict, bytes]:
        """One request; returns (status, lowercased headers, payload).

        Connection-level faults (refused, reset, timed out, truncated)
        surface as ``ClientTransientError`` — the transport layer cannot
        produce a permanent verdict, only a status line can. A REUSED
        connection that dies before yielding any response byte is the
        normal stale-keep-alive race (the server idled it out between our
        requests): retried once on a fresh connection before the error
        surfaces. ONLY that shape retries — once a response byte arrived
        (the request was definitely processed) or on a timeout (the peer
        may still be processing), a silent re-send could duplicate a
        non-idempotent POST; those surface to the caller's retry policy,
        which understands protocol-level idempotence.
        """
        if self.path_prefix:
            path = self.path_prefix + path
        ctx = trace.current_ctx()
        if ctx is not None:
            # propagate the trace across the wire: the coordinator's REST
            # request span adopts this id (docs/DESIGN.md §16)
            headers = dict(headers or {})
            headers[trace.TRACE_HEADER] = trace.format_header(ctx)
        reused = self._checkout() if self.keep_alive else None
        for attempt in ("reused", "fresh"):
            if reused is not None:
                reader, writer = reused
            else:
                reader, writer = await self._connect()
            response_begun: list = []
            try:
                status, resp_headers, payload = await self._exchange(
                    reader, writer, method, path, body, headers, response_begun
                )
            except (OSError, asyncio.TimeoutError, asyncio.IncompleteReadError,
                    ValueError, IndexError) as err:
                # ValueError/IndexError: garbled status line from a dying peer
                writer.close()
                if (
                    reused is not None
                    and attempt == "reused"
                    and not response_begun
                    and not isinstance(err, (asyncio.TimeoutError, TimeoutError))
                ):
                    reused = None  # stale pooled connection: one fresh retry
                    continue
                raise ClientTransientError(f"{method} {path}: {err}") from err
            except BaseException:
                writer.close()
                raise
            self._checkin(
                reader,
                writer,
                resp_headers.get("connection", "keep-alive").lower() != "close",
            )
            return status, resp_headers, payload
        raise AssertionError("unreachable")  # pragma: no cover

    async def _exchange(
        self, reader, writer, method: str, path: str, body: bytes | None,
        extra_headers: Optional[dict] = None, response_begun: Optional[list] = None,
    ) -> tuple[int, dict, bytes]:
        # self.timeout bounds each individual read as an IDLE timeout, not
        # the whole exchange: a peer that stalls mid-response fails fast
        # (transient, the wrapper retries), while a large model download
        # that keeps making progress on a slow link is never cut off
        extra = "".join(
            f"{name}: {value}\r\n" for name, value in (extra_headers or {}).items()
        )
        connection = "keep-alive" if self.keep_alive else "close"
        head = (
            f"{method} {path} HTTP/1.1\r\nHost: {self.host}\r\n"
            f"Content-Length: {len(body) if body else 0}\r\n"
            f"{extra}"
            f"Connection: {connection}\r\n\r\n"
        ).encode()
        writer.write(head + (body or b""))
        await asyncio.wait_for(writer.drain(), self.timeout)
        status_line = await asyncio.wait_for(reader.readline(), self.timeout)
        if status_line and response_begun is not None:
            response_begun.append(True)  # any byte back: request was processed
        status = int(status_line.split()[1])
        headers: dict[str, str] = {}
        content_length = 0
        while True:
            line = await asyncio.wait_for(reader.readline(), self.timeout)
            if line in (b"\r\n", b""):
                break
            name, _, value = line.decode().partition(":")
            headers[name.strip().lower()] = value.strip()
            if name.strip().lower() == "content-length":
                content_length = int(value.strip())
        chunks = []
        remaining = content_length
        while remaining > 0:
            chunk = await asyncio.wait_for(
                reader.read(min(remaining, 1 << 20)), self.timeout
            )
            if not chunk:  # peer closed mid-body
                raise asyncio.IncompleteReadError(b"".join(chunks), content_length)
            chunks.append(chunk)
            remaining -= len(chunk)
        return status, headers, b"".join(chunks)

    @staticmethod
    def _retry_after(headers: dict) -> Optional[float]:
        value = headers.get("retry-after")
        if value is None:
            return None
        try:
            return max(0.0, float(value))
        except ValueError:
            return None  # HTTP-date flavor: ignore, the backoff still works

    def _raise_for_status(self, status: int, headers: dict, context: str) -> None:
        # anything outside 2xx fails: the client never follows redirects, so
        # a 3xx "success" would silently lose the call behind a misconfigured
        # proxy (the body would be an HTML redirect page, not protocol JSON)
        if status < 300:
            return
        raise classify_status(status, self._retry_after(headers), context)

    async def get_round_params(self) -> RoundParameters:
        status, headers, body = await self._request("GET", "/params")
        self._raise_for_status(status, headers, "GET /params")
        return RoundParameters.from_dict(json.loads(body.decode()))

    async def get_sums(self) -> Optional[dict]:
        status, headers, body = await self._request("GET", "/sums")
        if status == 204:
            return None
        self._raise_for_status(status, headers, "GET /sums")
        raw = json.loads(body.decode())
        return {bytes.fromhex(k): bytes.fromhex(v) for k, v in raw.items()}

    async def get_seeds(self, pk: bytes) -> Optional[UpdateSeedDict]:
        from ..core.mask.seed import EncryptedMaskSeed, unpack_seed_entries

        # request the batched binary fan-out (§21: 112 B/entry fixed
        # frames); a pre-v2 coordinator ignores the fmt param and answers
        # JSON — dispatch on the response content type, so either end can
        # be upgraded first
        status, headers, body = await self._request(
            "GET", f"/seeds?pk={pk.hex()}&fmt=bin"
        )
        if status == 204:
            return None
        self._raise_for_status(status, headers, "GET /seeds")
        if headers.get("content-type", "").startswith("application/octet-stream"):
            return unpack_seed_entries(body)
        raw = json.loads(body.decode())
        return {bytes.fromhex(k): EncryptedMaskSeed(bytes.fromhex(v)) for k, v in raw.items()}

    async def get_model(self) -> Optional[np.ndarray]:
        status, headers, body = await self._request("GET", "/model")
        if status == 204:
            return None
        self._raise_for_status(status, headers, "GET /model")
        return np.frombuffer(body, dtype=np.float64)

    async def send_message(self, encrypted: bytes) -> None:
        status, headers, body = await self._request("POST", "/message", encrypted)
        self._raise_for_status(status, headers, f"POST /message: {body[:200]!r}")


def default_client_policy() -> RetryPolicy:
    """Participant-side retry defaults: a handful of quick in-tick retries.

    Deliberately shorter than the coordinator's storage policy — a
    participant tick should resolve in seconds; anything longer is the
    state machine's job (it stays in phase and re-polls on later ticks)."""
    return RetryPolicy(
        max_attempts=4, base_delay_s=0.05, max_delay_s=2.0, deadline_s=15.0
    )


class ResilientClient(XaynetClient):
    """Retry wrapper around any ``XaynetClient``.

    Transient failures (``ClientTransientError``, connection-ish builtins
    per ``resilience.policy.is_transient``) retry in place on the policy's
    decorrelated-jitter schedule; a server-sent ``Retry-After`` (429/503)
    acts as a FLOOR under the drawn delay, so a shedding admission
    controller is never hammered faster than it asked for. Permanent
    errors propagate on the first attempt.

    Fault-injection sites (chaos, ``resilience.faults``):

    - ``sdk.straggle`` — latency rules delay a send (a straggling radio);
    - ``sdk.drop`` — the send is silently DROPPED: the client believes it
      succeeded, the coordinator never sees the message (a lost packet);
    - ``sdk.send`` — error rules fail a send attempt (retried like any
      transient fault; ``perm=1`` makes it permanent).
    """

    # endpoint -> span name; subclasses with extra endpoints extend this
    SPANS = _ENDPOINT_SPANS

    def __init__(self, inner: XaynetClient, policy: Optional[RetryPolicy] = None):
        self.inner = inner
        self.policy = policy if policy is not None else default_client_policy()
        # the round's trace context: set from the round seed by the SDK
        # state machine (or the edge sync loop), so every tier derives the
        # SAME trace id for one round; None = each call starts a fresh
        # trace (this client GENERATES ids either way)
        self.trace_ctx: Optional[trace.TraceContext] = None

    def set_round_trace(self, round_seed: Optional[bytes]) -> None:
        """Pin this client's calls to the round's deterministic trace."""
        if round_seed is None:
            self.trace_ctx = None
        else:
            self.trace_ctx = trace.TraceContext(trace.round_trace_id(round_seed))

    def close(self) -> None:
        """Release the wrapped transport's pooled connections (if any)."""
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()

    async def _call(self, endpoint: str, fn, *args):
        # the shared policy loop carries the per-site retry/giveup/backoff
        # metrics (xaynet_resilience_*_total{site="sdk.<endpoint>"}); the
        # server-sent Retry-After floors the drawn delay via the hook
        name = self.SPANS.get(endpoint)
        tracer = trace.get_tracer()
        if name is None or tracer.mode == "off":
            return await self.policy.call_async(
                fn,
                *args,
                site=f"sdk.{endpoint}",
                delay_floor=lambda err: getattr(err, "retry_after", None),
            )
        # one logical-call span; every retry attempt is a CHILD span whose
        # context rides the wire (X-Xaynet-Trace carries the attempt id, so
        # the server can tell which attempt it served)
        attempts = 0

        async def one_attempt(*call_args):
            nonlocal attempts
            attempts += 1
            with tracer.span(SPAN_ATTEMPT, attempt=attempts):
                return await fn(*call_args)

        ctx = self.trace_ctx
        if ctx is None and trace.current_ctx() is None:
            ctx = trace.TraceContext(trace.new_id())
        with tracer.span(name, ctx=ctx) as span:
            try:
                return await self.policy.call_async(
                    one_attempt,
                    *args,
                    site=f"sdk.{endpoint}",
                    delay_floor=lambda err: getattr(err, "retry_after", None),
                )
            finally:
                span.set(attempts=attempts)

    async def get_round_params(self) -> RoundParameters:
        return await self._call("params", self.inner.get_round_params)

    async def get_sums(self) -> Optional[dict]:
        return await self._call("sums", self.inner.get_sums)

    async def get_seeds(self, pk: bytes) -> Optional[UpdateSeedDict]:
        return await self._call("seeds", self.inner.get_seeds, pk)

    async def get_model(self) -> Optional[np.ndarray]:
        return await self._call("model", self.inner.get_model)

    async def send_message(self, encrypted: bytes) -> None:
        from ..resilience import faults

        plan = faults.current_plan()
        if plan is not None:
            # participant-side chaos: straggle (delay) then maybe drop this
            # send on the wire — both once per LOGICAL send, not per retry
            await faults.maybe_fail_async("sdk.straggle")
            if plan.decide("sdk.drop") is not None:
                CLIENT_DROPS.inc()
                logger.debug("sdk.drop: send silently dropped by fault plan")
                return
        await self._call("send", self._send_attempt, encrypted)

    async def _send_attempt(self, encrypted: bytes) -> None:
        from ..resilience import faults

        await faults.maybe_fail_async("sdk.send")
        await self.inner.send_message(encrypted)
