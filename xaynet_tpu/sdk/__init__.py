"""Participant SDK: state machine, clients, embeddable + high-level APIs.

Reference surface: rust/xaynet-sdk/ (FSM, client, encoder),
rust/xaynet-mobile/ (tick-driven Participant), bindings/python/xaynet_sdk
(ParticipantABC / AsyncParticipant / spawn_*).
"""

from .api import (
    AsyncParticipant,
    InternalParticipant,
    ParticipantABC,
    spawn_async_participant,
    spawn_participant,
)
from .client import (
    ClientError,
    ClientPermanentError,
    ClientShedError,
    ClientTransientError,
    HttpClient,
    InProcessClient,
    ResilientClient,
)
from .participant import Participant
from .state_machine import PetSettings, PhaseKind, StateMachine, Task, TransitionOutcome
from .traits import ModelStore, Notify, XaynetClient

__all__ = [
    "AsyncParticipant",
    "InternalParticipant",
    "ParticipantABC",
    "spawn_async_participant",
    "spawn_participant",
    "ClientError",
    "ClientPermanentError",
    "ClientShedError",
    "ClientTransientError",
    "HttpClient",
    "InProcessClient",
    "ResilientClient",
    "Participant",
    "PetSettings",
    "PhaseKind",
    "StateMachine",
    "Task",
    "TransitionOutcome",
    "ModelStore",
    "Notify",
    "XaynetClient",
]
