"""High-level participant API: the user-facing training integration.

Functional port of the reference's Python binding surface (reference:
bindings/python/xaynet_sdk/__init__.py, participant.py:20-243,
async_participant.py:15-140):

- ``ParticipantABC``: subclass and implement ``train_round`` (plus optional
  (de)serialization hooks); ``spawn_participant`` runs the PET protocol on a
  background thread and calls back into your trainer;
- ``AsyncParticipant``: no subclassing — a handle to set the next model at
  any time and fetch the latest global model.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from abc import ABC, abstractmethod
from fractions import Fraction
from typing import Optional

import numpy as np

from .participant import Participant, coerce_model_array

logger = logging.getLogger("xaynet.sdk")


class ParticipantABC(ABC):
    """Implement your local training against this interface."""

    @abstractmethod
    def train_round(self, training_input: Optional[np.ndarray]) -> np.ndarray:
        """One round of local training; input is the current global model
        (None in the first round)."""

    def serialize_training_result(self, result) -> np.ndarray:
        return np.asarray(result, dtype=np.float32)

    def deserialize_training_input(self, global_model: np.ndarray):
        return global_model

    def on_new_global_model(self, model) -> None:
        """Called whenever a new global model is available."""

    def participate_in_update_task(self) -> bool:
        return True

    def on_stop(self) -> None:
        """Called when the participant thread exits."""


class InternalParticipant(threading.Thread):
    """Drives the tick loop and the user's trainer on a background thread."""

    def __init__(
        self,
        coordinator_url: str,
        participant: ParticipantABC,
        state: Optional[bytes],
        scalar: Fraction,
        tick_interval: float = 0.1,
        keys=None,
    ):
        super().__init__(daemon=True)
        self._participant = participant
        self._inner = Participant(coordinator_url, scalar=scalar, state=state, keys=keys)
        self._exit = threading.Event()
        self._tick_interval = tick_interval
        self._global_model: Optional[np.ndarray] = None

    def run(self) -> None:
        try:
            while not self._exit.is_set():
                self._inner.tick()
                if self._inner.new_global_model():
                    # new round: the previous round's local model is stale
                    self._inner.clear_model()
                    model = self._inner.global_model()
                    if model is not None and (
                        self._global_model is None
                        or not np.array_equal(model, self._global_model)
                    ):
                        self._global_model = model
                        self._participant.on_new_global_model(
                            self._participant.deserialize_training_input(model)
                        )
                if self._inner.should_set_model() and self._participant.participate_in_update_task():
                    training_input = (
                        self._participant.deserialize_training_input(self._global_model)
                        if self._global_model is not None
                        else None
                    )
                    result = self._participant.train_round(training_input)
                    self._inner.set_model(self._participant.serialize_training_result(result))
                if not self._inner.made_progress():
                    time.sleep(self._tick_interval)
        finally:
            self._participant.on_stop()

    def stop(self) -> Optional[bytes]:
        """Stops the thread and returns the serialized participant state."""
        self._exit.set()
        self.join(timeout=10)
        return self._inner.save()


def spawn_participant(
    coordinator_url: str,
    participant_class: type[ParticipantABC],
    args: tuple = (),
    kwargs: Optional[dict] = None,
    state: Optional[bytes] = None,
    scalar: Fraction = Fraction(1),
    keys=None,
) -> InternalParticipant:
    """Spawns and starts a participant driving ``participant_class``.

    ``keys`` pins the signing keypair (simulations need deterministic
    roles); omitted in production, where keys are generated per participant.
    """
    participant = participant_class(*args, **(kwargs or {}))
    thread = InternalParticipant(coordinator_url, participant, state, scalar, keys=keys)
    thread.start()
    return thread


class AsyncParticipant(threading.Thread):
    """Set a model whenever you like; the FSM picks the latest one up."""

    def __init__(
        self,
        coordinator_url: str,
        state: Optional[bytes],
        scalar: Fraction,
        tick_interval: float = 0.1,
    ):
        super().__init__(daemon=True)
        self._inner = Participant(coordinator_url, scalar=scalar, state=state)
        self._exit = threading.Event()
        self._tick_interval = tick_interval
        self._model_queue: "queue.Queue[np.ndarray]" = queue.Queue()
        self._global_model: Optional[np.ndarray] = None
        self._new_global = threading.Event()

    def run(self) -> None:
        while not self._exit.is_set():
            try:
                while True:
                    self._inner.set_model(self._model_queue.get_nowait())
            except queue.Empty:
                pass
            self._inner.tick()
            if self._inner.new_global_model():
                model = self._inner.global_model()
                if model is not None and (
                    self._global_model is None
                    or not np.array_equal(model, self._global_model)
                ):
                    self._global_model = model
                    self._new_global.set()
            if not self._inner.made_progress():
                time.sleep(self._tick_interval)

    def set_model(self, model) -> None:
        self._model_queue.put(coerce_model_array(model))

    def get_global_model(self, timeout: Optional[float] = None) -> Optional[np.ndarray]:
        self._new_global.wait(timeout)
        self._new_global.clear()
        return self._global_model

    def stop(self) -> Optional[bytes]:
        self._exit.set()
        self.join(timeout=10)
        return self._inner.save()


def spawn_async_participant(
    coordinator_url: str,
    state: Optional[bytes] = None,
    scalar: Fraction = Fraction(1),
) -> AsyncParticipant:
    thread = AsyncParticipant(coordinator_url, state, scalar)
    thread.start()
    return thread
