"""Simulation helpers: deterministic role assignment for in-process rounds.

PET task selection is probabilistic over each participant's Ed25519 key and
the round seed. For simulations and tests we need participants with *known*
roles, so we rejection-sample signing keys until the eligibility check lands
on the desired task — the protocol itself stays untouched.
"""

from __future__ import annotations

from ..core.crypto.sign import SigningKeyPair, is_eligible


def keys_for_task(
    round_seed: bytes,
    sum_prob: float,
    update_prob: float,
    want: str,
    start: int = 0,
    max_tries: int = 100_000,
) -> SigningKeyPair:
    """Finds a signing keypair whose task for this round is ``want``.

    ``want`` is "sum", "update" or "none". Deterministic given ``start``.
    """
    for i in range(start, start + max_tries):
        keys = SigningKeyPair.derive_from_seed(i.to_bytes(32, "little"))
        sum_sig = keys.sign(round_seed + b"sum").as_bytes()
        update_sig = keys.sign(round_seed + b"update").as_bytes()
        if is_eligible(sum_sig, sum_prob):
            role = "sum"
        elif is_eligible(update_sig, update_prob):
            role = "update"
        else:
            role = "none"
        if role == want:
            return keys
    raise RuntimeError(f"no key found for task {want} in {max_tries} tries")
