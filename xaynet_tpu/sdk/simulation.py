"""Simulation helpers: deterministic role assignment and load generation.

PET task selection is probabilistic over each participant's Ed25519 key and
the round seed. For simulations and tests we need participants with *known*
roles, so we rejection-sample signing keys until the eligibility check lands
on the desired task — the protocol itself stays untouched.

``flood`` drives N concurrent, fully valid update uploads (deterministic
keys via ``keys_for_task``) against a ``PetMessageHandler`` or an
``ingest.IngestPipeline`` — the load generator behind the shed/admit stress
tests.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from fractions import Fraction
from typing import Callable, Optional, Sequence

import numpy as np

from ..core.common import RoundParameters
from ..core.crypto.encrypt import PublicEncryptKey
from ..core.crypto.sign import SigningKeyPair, is_eligible
from ..core.mask.masking import Masker
from ..core.mask.model import Scalar
from ..core.message import Message, Update


def keys_for_task(
    round_seed: bytes,
    sum_prob: float,
    update_prob: float,
    want: str,
    start: int = 0,
    max_tries: int = 100_000,
) -> SigningKeyPair:
    """Finds a signing keypair whose task for this round is ``want``.

    ``want`` is "sum", "update" or "none". Deterministic given ``start``.
    """
    for i in range(start, start + max_tries):
        keys = SigningKeyPair.derive_from_seed(i.to_bytes(32, "little"))
        sum_sig = keys.sign(round_seed + b"sum").as_bytes()
        update_sig = keys.sign(round_seed + b"update").as_bytes()
        if is_eligible(sum_sig, sum_prob):
            role = "sum"
        elif is_eligible(update_sig, update_prob):
            role = "update"
        else:
            role = "none"
        if role == want:
            return keys
    raise RuntimeError(f"no key found for task {want} in {max_tries} tries")


def build_update_message(
    params: RoundParameters,
    keys: SigningKeyPair,
    sum_dict: dict,
    model,
    scalar: Fraction = Fraction(1),
    wire_planar: Optional[bool] = None,
) -> bytes:
    """One fully valid, sealed update upload for an update-task participant.

    The exact client-side pipeline (mask -> seed-dict encrypt -> sign ->
    sealed box) without the participant state machine around it — what a
    load generator needs. ``wire_planar=None`` follows the round's
    negotiated wire format (``params.wire_format``); an explicit bool
    forces the v2 planar / v1 interleaved element layout.
    """
    masker = Masker(params.mask_config)
    seed, masked_model = masker.mask(Scalar.from_fraction(scalar), np.asarray(model))
    if wire_planar is None:
        wire_planar = params.wire_format >= 2
    payload = Update(
        sum_signature=keys.sign(params.seed.as_bytes() + b"sum").as_bytes(),
        update_signature=keys.sign(params.seed.as_bytes() + b"update").as_bytes(),
        masked_model=masked_model,
        local_seed_dict={
            sum_pk: seed.encrypt(PublicEncryptKey(ephm_pk))
            for sum_pk, ephm_pk in sum_dict.items()
        },
        wire_planar=wire_planar,
    )
    message = Message(participant_pk=keys.public, coordinator_pk=params.pk, payload=payload)
    return PublicEncryptKey(params.pk).encrypt(message.to_bytes(keys.secret))


@dataclass
class FloodStats:
    """Outcome counts of one ``flood`` run.

    ``accepted`` means the target took the message (handler completed, or
    the pipeline admitted it — admitted messages resolve asynchronously);
    ``rejected`` counts pipeline-stage/protocol drops surfaced at submit
    time; ``shed`` counts admission-control refusals (429 upstream).
    Chaos knobs add ``dropped`` (participants whose upload was never sent)
    and ``straggled`` (sent late); the index tuples name exactly who, so a
    test can rebuild the surviving participant set.
    """

    sent: int = 0
    accepted: int = 0
    rejected: int = 0
    shed: int = 0
    dropped: int = 0
    straggled: int = 0
    dropped_indices: tuple = ()
    straggled_indices: tuple = ()


def plan_churn(
    n: int, dropout_rate: float, stragglers: int, seed: int
) -> tuple[frozenset, frozenset]:
    """Deterministic churn assignment for ``flood``: which of the ``n``
    participants drop out entirely and which straggle. Seeded — a chaos
    test and its byte-identity control run agree on the survivor set."""
    if not (0.0 <= dropout_rate < 1.0):
        raise ValueError("dropout_rate must be in [0, 1)")
    rng = random.Random(seed)
    n_drop = int(round(n * dropout_rate))
    dropped = frozenset(rng.sample(range(n), n_drop)) if n_drop else frozenset()
    remaining = sorted(set(range(n)) - dropped)
    n_straggle = min(max(0, stragglers), len(remaining))
    straggled = (
        frozenset(rng.sample(remaining, n_straggle)) if n_straggle else frozenset()
    )
    return dropped, straggled


async def flood(
    target,
    params: RoundParameters,
    sum_dict: dict,
    n: int,
    *,
    models: Optional[Sequence] = None,
    scalar: Optional[Fraction] = None,
    key_start: int = 0,
    key_spacing: int = 1000,
    concurrency: int = 64,
    build: Optional[Callable[[int], bytes]] = None,
    dropout_rate: float = 0.0,
    stragglers: int = 0,
    straggle_delay_s: float = 0.2,
    churn_seed: Optional[int] = None,
) -> FloodStats:
    """Drive ``n`` concurrent valid update uploads against ``target``.

    ``target`` is a ``PetMessageHandler`` (awaits each message's verdict),
    an ``ingest.IngestPipeline`` (admission verdicts), or any async callable
    of one ``bytes`` argument. Keys are deterministic — participant ``i``
    searches from ``key_start + i * key_spacing`` — so repeated floods in
    the same round collide on purpose (duplicate-participant rejections)
    and distinct ``key_start`` ranges never do. ``build`` overrides message
    construction (e.g. pre-sealed garbage for decrypt-path floods).

    Churn knobs (chaos scenarios, docs/DESIGN.md §10): ``dropout_rate``
    silently withholds that fraction of the uploads (the participants
    trained, then vanished — the quorum-completion target), ``stragglers``
    delays that many of the surviving uploads by ``straggle_delay_s``.
    Assignment is deterministic per ``churn_seed`` (``plan_churn``), and
    the stats name the affected indices so a control run can rebuild the
    exact survivor set.
    """
    if models is None:
        rng = np.random.default_rng(key_start or 7)
        models = [
            rng.uniform(-1, 1, params.model_length).astype(np.float32) for _ in range(n)
        ]
    scalar = scalar if scalar is not None else Fraction(1, max(1, n))
    seed = params.seed.as_bytes()

    def default_build(i: int) -> bytes:
        keys = keys_for_task(
            seed, params.sum, params.update, "update", start=key_start + i * key_spacing
        )
        return build_update_message(params, keys, sum_dict, models[i % len(models)], scalar)

    build = build or default_build
    # seed 0 is a valid explicit choice — only None falls back to key_start
    # (a control run on a different key range must reuse the chaos run's
    # churn_seed and get the identical survivor set)
    if churn_seed is None:
        churn_seed = key_start or 7
    dropped, straggled = plan_churn(n, dropout_rate, stragglers, churn_seed)
    # sealing is CPU-bound and deterministic: do it before the clock starts
    # (dropouts never sent anything — don't pay for sealing them either)
    sealed = {i: build(i) for i in range(n) if i not in dropped}

    submit = _submitter(target)
    stats = FloodStats(
        dropped=len(dropped),
        straggled=len(straggled),
        dropped_indices=tuple(sorted(dropped)),
        straggled_indices=tuple(sorted(straggled)),
    )
    gate = asyncio.Semaphore(max(1, concurrency))

    async def one(i: int, blob: bytes) -> None:
        if i in straggled:
            # outside the gate: a straggler must not hold a concurrency slot
            # while it sleeps
            await asyncio.sleep(straggle_delay_s)
        async with gate:
            stats.sent += 1
            outcome = await submit(blob)
            setattr(stats, outcome, getattr(stats, outcome) + 1)

    await asyncio.gather(*(one(i, blob) for i, blob in sealed.items()))
    return stats


def _submitter(target):
    """Normalize the three target kinds to ``async (bytes) -> outcome``."""
    from ..server.requests import RequestError
    from ..server.services import ServiceError

    if hasattr(target, "submit"):  # ingest.IngestPipeline

        async def submit_pipeline(blob: bytes) -> str:
            verdict = await target.submit(blob)
            if verdict.shed:
                return "shed"
            return "accepted" if verdict.verdict.value == "admitted" else "rejected"

        return submit_pipeline

    if hasattr(target, "handle_message"):  # PetMessageHandler

        async def submit_handler(blob: bytes) -> str:
            try:
                await target.handle_message(blob)
                return "accepted"
            except (ServiceError, RequestError):
                return "rejected"

        return submit_handler

    async def submit_callable(blob: bytes) -> str:
        await target(blob)
        return "accepted"

    return submit_callable
