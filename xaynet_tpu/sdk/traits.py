"""Participant-side interfaces.

Reference surface: rust/xaynet-sdk/src/traits.rs:15-73 — the coordinator
client (five endpoints), the model store (hands the locally trained model to
the FSM) and the notifier (progress callbacks into the embedding
application).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

import numpy as np

from ..core.common import RoundParameters, UpdateSeedDict


class XaynetClient(ABC):
    """Transport to the coordinator (HTTP in production, in-process in tests)."""

    @abstractmethod
    async def get_round_params(self) -> RoundParameters: ...

    @abstractmethod
    async def get_sums(self) -> Optional[dict]:
        """The sum dictionary, or None while unavailable."""

    @abstractmethod
    async def get_seeds(self, pk: bytes) -> Optional[UpdateSeedDict]:
        """This sum participant's seed slice, or None while unavailable."""

    @abstractmethod
    async def get_model(self) -> Optional[np.ndarray]:
        """The latest global model, or None while unavailable."""

    @abstractmethod
    async def send_message(self, encrypted: bytes) -> None: ...


class ModelStore(ABC):
    """Hands the locally trained model to the FSM when it is needed."""

    @abstractmethod
    async def load_model(self) -> Optional[np.ndarray]:
        """The trained model as a float array, or None when not ready yet."""


class Notify:
    """Progress callbacks; override what the application cares about."""

    def new_round(self) -> None: ...

    def sum(self) -> None: ...

    def update(self) -> None: ...

    def idle(self) -> None: ...

    def load_model(self) -> None:
        """The FSM needs a trained model (the store returned None)."""

    def new_model(self, model) -> None:
        """A new global model was fetched."""
