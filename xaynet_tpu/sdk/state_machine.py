"""Participant state machine: the client half of the PET protocol.

Functional port of the reference's poll-driven FSM (reference:
rust/xaynet-sdk/src/state_machine/): phases Awaiting -> NewRound ->
(Sum -> Sum2 | Update) -> Awaiting. Every ``transition()`` first re-polls
the round parameters; a parameter change resets the machine to NewRound
(phase.rs:160-200), which is what makes participants tolerant of coordinator
restarts and round cuts.

The whole machine state is serializable (``save()`` / ``restore()``,
reference: state_machine.rs:54-148) so an embedding application can suspend
at any point.
"""

from __future__ import annotations

import asyncio
import base64
import enum
import json
import logging
from dataclasses import dataclass
from fractions import Fraction
from typing import Optional

import numpy as np

from ..core.common import RoundParameters
from ..core.crypto.encrypt import EncryptKeyPair, PublicEncryptKey
from ..core.crypto.sign import SigningKeyPair, is_eligible
from ..core.mask.masking import Aggregation, Masker
from ..core.mask.model import Scalar
from ..core.mask.object import MaskObject
from ..core.message import Message, Sum, Sum2, Update
from ..core.message.encoder import DEFAULT_MAX_MESSAGE_SIZE, MIN_MESSAGE_SIZE, MessageEncoder
from .traits import ModelStore, Notify, XaynetClient

logger = logging.getLogger("xaynet.participant")


def _is_transient_client_error(err: BaseException) -> bool:
    """Worth retrying within the same round? Typed markers win
    (``ClientError.transient``); unmarked connection/timeout builtins are
    transient too (a custom ``XaynetClient`` raising raw socket errors).
    Deliberately NARROWER than ``resilience.policy.is_transient``: a
    generic ``OSError`` here is more likely a local fault (a model store's
    ``FileNotFoundError``) than a network one — treating it as transient
    would spin the participant on PENDING forever, so it propagates."""
    marker = getattr(err, "transient", None)
    if marker is not None:
        return bool(marker)
    return isinstance(err, (ConnectionError, TimeoutError, asyncio.TimeoutError))


_ACCEL_DEFAULT: Optional[bool] = None


def _default_backend_is_accelerator() -> bool:
    """True when JAX's default backend is an accelerator (TPU/GPU).

    Resolved lazily and memoized: the ``device_sum2=None`` auto default must
    not initialize a JAX backend for CPU-only participants that never reach
    a Sum2 leg, and a broken/absent JAX install simply means host kernels.
    """
    global _ACCEL_DEFAULT
    if _ACCEL_DEFAULT is None:
        try:
            import jax

            _ACCEL_DEFAULT = jax.default_backend() != "cpu"
        except Exception:
            _ACCEL_DEFAULT = False
    return _ACCEL_DEFAULT


class TransitionOutcome(enum.Enum):
    PENDING = "pending"  # no progress possible right now; retry later
    COMPLETE = "complete"  # made progress


class Task(enum.Enum):
    NONE = "none"
    SUM = "sum"
    UPDATE = "update"


class PhaseKind(str, enum.Enum):
    AWAITING = "awaiting"
    NEW_ROUND = "new_round"
    SUM = "sum"
    UPDATE = "update"
    SUM2 = "sum2"


@dataclass
class PetSettings:
    """Participant settings (reference: xaynet-sdk/src/settings/mod.rs:8-23)."""

    keys: SigningKeyPair
    scalar: Fraction = Fraction(1)
    max_message_size: Optional[int] = DEFAULT_MAX_MESSAGE_SIZE
    # run the Sum2 mask expansion/aggregation on the JAX device. None (the
    # default) auto-enables it exactly when an accelerator backend is
    # already the JAX default — device-equipped participants get the device
    # path without opting in, while CPU-only edges never initialize an
    # accelerator runtime they don't have (VERDICT r3 item 8). Set an
    # explicit False to keep the host path on accelerator hosts.
    device_sum2: Optional[bool] = None
    # when the device path is requested, fail loudly instead of silently
    # falling back to the host path (tests set this so a broken device
    # kernel cannot hide behind the fallback)
    device_sum2_strict: bool = False
    # Sum2 mask derive+sum route (utils.kernels.MASK_KERNELS): "auto" (the
    # default) lets masking_jax race the candidates once per process;
    # explicit values PIN the route — and therefore engage the promoted
    # pipeline at any model size (only an explicit device_sum2=False
    # overrides a pin back to the legacy host path). The oracle pins each
    # leg this way.
    mask_kernel: str = "auto"
    # deterministic mask seed for the Update task (32 bytes). None (the
    # default, and the only safe production value) draws a fresh random
    # seed per update exactly like the reference; injecting a fixed seed
    # makes the masked model and seed dictionary reproducible, which is
    # what the differential oracle (xaynet_tpu.sim.oracle) needs to replay
    # one round through both the server and the in-graph simulation.
    mask_seed: Optional[bytes] = None

    def __post_init__(self):
        if self.max_message_size is not None and self.max_message_size < MIN_MESSAGE_SIZE:
            raise ValueError(
                f"max_message_size must be None or >= {MIN_MESSAGE_SIZE} "
                "(header + chunk header + 1 byte of progress)"
            )
        if self.mask_seed is not None and len(self.mask_seed) != 32:
            raise ValueError("mask_seed must be exactly 32 bytes")
        from ..utils.kernels import MASK_KERNELS

        if self.mask_kernel not in MASK_KERNELS:
            raise ValueError(
                "mask_kernel must be one of: " + " | ".join(MASK_KERNELS)
            )


@dataclass
class _RawPayload:
    """Pre-serialized payload bytes (restoring an in-flight send)."""

    raw: bytes

    def to_bytes(self) -> bytes:
        return self.raw

    def serialized_length(self) -> int:
        return len(self.raw)


class _PendingSend:
    """An in-flight multipart send: encoder + next undelivered part."""

    def __init__(self, encoder: MessageEncoder, coordinator_pk: bytes, next_index: int = 0):
        self.encoder = encoder
        self.coordinator_pk = PublicEncryptKey(coordinator_pk)
        self.next_index = next_index

    def sealed_part(self, i: int) -> bytes:
        return self.coordinator_pk.encrypt(self.encoder.part(i))


class StateMachine:
    """Poll-driven participant FSM."""

    def __init__(
        self,
        settings: PetSettings,
        client: XaynetClient,
        model_store: ModelStore,
        notify: Optional[Notify] = None,
    ):
        self.keys = settings.keys
        self.scalar = settings.scalar
        self.max_message_size = settings.max_message_size
        self.device_sum2 = settings.device_sum2
        self.device_sum2_strict = settings.device_sum2_strict
        self.mask_kernel = settings.mask_kernel
        self.mask_seed = settings.mask_seed
        self.client = client
        self.model_store = model_store
        self.notify = notify or Notify()

        self.phase = PhaseKind.AWAITING
        self.round_params: Optional[RoundParameters] = None
        self.task = Task.NONE
        self.sum_signature: Optional[bytes] = None
        self.update_signature: Optional[bytes] = None
        self.ephm_keys: Optional[EncryptKeyPair] = None
        # chunk-level send retry (reference: sending.rs:96-113): the
        # in-flight multipart send is ONE payload copy plus a part index —
        # each part is signed+sealed lazily when its turn comes, so a
        # paused 270MB send doesn't hold a second materialized part list.
        # Delivered parts are never re-sent.
        self._pending: Optional[_PendingSend] = None
        self._after_send_phase: Optional[PhaseKind] = None

    # --- driving ----------------------------------------------------------

    async def transition(self) -> TransitionOutcome:
        """One step; checks round freshness first (phase.rs:160-200).

        A TRANSIENT client failure inside a phase step (a dropped
        connection, a 429/503 the retry wrapper gave up on) does NOT abort
        the round: the machine stays in its phase and reports PENDING — the
        next tick re-polls the round params and, while the round is
        unchanged, resumes exactly where it left off (signatures, ephemeral
        keys and the send cursor are all kept). Only permanent errors
        propagate to the caller."""
        try:
            fresh = await self.client.get_round_params()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if getattr(e, "transient", None) is False:
                # typed PERMANENT client error (404 from a wrong URL, ...):
                # re-polling cannot heal it — surface the misconfiguration
                # instead of ticking PENDING forever
                raise
            logger.debug("round params unavailable: %s", e)
            return TransitionOutcome.PENDING
        if self.round_params is None or fresh != self.round_params:
            self.round_params = fresh
            self._reset_round_state()
            self.phase = PhaseKind.NEW_ROUND
            self.notify.new_round()
            # pin the client's spans to the round's deterministic trace id
            # (derived from the public seed) so the participant's uploads
            # stitch into the coordinator's round trace (DESIGN §16)
            set_round_trace = getattr(self.client, "set_round_trace", None)
            if set_round_trace is not None:
                set_round_trace(fresh.seed.as_bytes())

        if self._pending is not None:
            return await self._drain_sends()

        handler = {
            PhaseKind.AWAITING: self._step_awaiting,
            PhaseKind.NEW_ROUND: self._step_new_round,
            PhaseKind.SUM: self._step_sum,
            PhaseKind.UPDATE: self._step_update,
            PhaseKind.SUM2: self._step_sum2,
        }[self.phase]
        try:
            return await handler()
        except asyncio.CancelledError:
            raise
        except Exception as err:
            if _is_transient_client_error(err):
                logger.info(
                    "transient client failure in %s (%s); staying in phase "
                    "and retrying on a later tick",
                    self.phase.value,
                    err,
                )
                return TransitionOutcome.PENDING
            raise

    def _reset_round_state(self) -> None:
        self.task = Task.NONE
        self.sum_signature = None
        self.update_signature = None
        self.ephm_keys = None
        self._pending = None
        self._after_send_phase = None

    # --- phases -----------------------------------------------------------

    async def _step_awaiting(self) -> TransitionOutcome:
        self.notify.idle()
        return TransitionOutcome.PENDING

    async def _step_new_round(self) -> TransitionOutcome:
        """Sign the round tasks and check eligibility (new_round.rs:29-79)."""
        assert self.round_params is not None
        seed = self.round_params.seed.as_bytes()
        self.sum_signature = self.keys.sign(seed + b"sum").as_bytes()
        self.update_signature = self.keys.sign(seed + b"update").as_bytes()

        if is_eligible(self.sum_signature, self.round_params.sum):
            self.task = Task.SUM
            self.phase = PhaseKind.SUM
            self.notify.sum()
        elif is_eligible(self.update_signature, self.round_params.update):
            self.task = Task.UPDATE
            self.phase = PhaseKind.UPDATE
            self.notify.update()
        else:
            self.task = Task.NONE
            self.phase = PhaseKind.AWAITING
            self.notify.idle()
        return TransitionOutcome.COMPLETE

    async def _step_sum(self) -> TransitionOutcome:
        """Send the ephemeral key, then wait for Sum2 (sum.rs:17-81)."""
        assert self.round_params is not None and self.sum_signature is not None
        if self.ephm_keys is None:
            self.ephm_keys = EncryptKeyPair.generate()
        payload = Sum(
            sum_signature=self.sum_signature,
            ephm_pk=self.ephm_keys.public.as_bytes(),
        )
        return await self._send(payload, PhaseKind.SUM2)

    async def _step_update(self) -> TransitionOutcome:
        """Train, mask, encrypt seeds, upload (update.rs:134-258)."""
        assert self.round_params is not None
        sum_dict = await self.client.get_sums()
        if not sum_dict:
            return TransitionOutcome.PENDING
        model = await self.model_store.load_model()
        if model is None:
            self.notify.load_model()
            return TransitionOutcome.PENDING
        if len(model) != self.round_params.model_length:
            raise ValueError(
                f"local model length {len(model)} != round model length "
                f"{self.round_params.model_length}"
            )
        # dtype vs the ROUND's mask config: integer weights on a float
        # config become the config's float width (f32 fast path when exact
        # to 2^24; f64 keeps integer exactness to 2^53)
        if isinstance(model, np.ndarray) and np.issubdtype(model.dtype, np.integer):
            from ..core.mask.config import DataType

            dt = self.round_params.mask_config.vect.data_type
            if dt is DataType.F32:
                model = model.astype(np.float32)
            elif dt is DataType.F64:
                model = model.astype(np.float64)

        if self.mask_seed is not None:
            from ..core.mask.seed import MaskSeed

            masker = Masker(self.round_params.mask_config, seed=MaskSeed(self.mask_seed))
        else:
            masker = Masker(self.round_params.mask_config)
        seed, masked_model = masker.mask(Scalar.from_fraction(self.scalar), model)
        local_seed_dict = {
            sum_pk: seed.encrypt(PublicEncryptKey(ephm_pk))
            for sum_pk, ephm_pk in sum_dict.items()
        }
        payload = Update(
            sum_signature=self.sum_signature,
            update_signature=self.update_signature,
            masked_model=masked_model,
            local_seed_dict=local_seed_dict,
            # honor the round's negotiated upload format (wire v2 planar)
            wire_planar=self.round_params.wire_format >= 2,
        )
        return await self._send(payload, PhaseKind.AWAITING)

    # with device_sum2 enabled, models above this size use the JAX device
    # kernels for mask derivation + aggregation (the Sum2 participant hot
    # loop: #updates x model_length group elements)
    DEVICE_SUM2_THRESHOLD = 262_144

    async def _step_sum2(self) -> TransitionOutcome:
        """Fetch seeds, derive + aggregate masks, upload (sum2.rs:82-204)."""
        assert self.round_params is not None and self.ephm_keys is not None
        seeds = await self.client.get_seeds(self.keys.public)
        if not seeds:
            return TransitionOutcome.PENDING

        length = self.round_params.model_length
        config = self.round_params.mask_config
        mask_seeds = [
            encrypted.decrypt(self.ephm_keys.secret, self.ephm_keys.public)
            for encrypted in seeds.values()
        ]
        mask_obj = self._aggregate_masks(mask_seeds, length, config)

        payload = Sum2(sum_signature=self.sum_signature, model_mask=mask_obj)
        return await self._send(payload, PhaseKind.AWAITING)

    def _aggregate_masks(self, mask_seeds, length: int, config) -> MaskObject:
        # getattr: tests build bare machines with __new__ and set only flags
        mask_kernel = getattr(self, "mask_kernel", "auto")
        pinned = mask_kernel not in (None, "auto")
        # an explicit device_sum2=True — or a PINNED mask_kernel (the
        # setting's contract: explicit values pin the route, so it must
        # actually engage the routed pipeline) — takes the promoted path
        # regardless of model size; an explicit device_sum2=False always
        # wins. Otherwise the length gate runs first, so small models never
        # pay for the accelerator probe (the auto default imports jax on
        # first resolution).
        use_device = (
            self.device_sum2 is True
            or (pinned and self.device_sum2 is not False)
            or (
                self.device_sum2 is not False
                and length >= self.DEVICE_SUM2_THRESHOLD
                and (
                    self.device_sum2
                    if self.device_sum2 is not None
                    else _default_backend_is_accelerator()
                )
            )
        )
        if use_device:
            try:
                from ..core.mask.object import MaskUnit, MaskVect
                from ..ops import masking_jax

                # the kwarg is only passed when pinned: the default route
                # stays masking_jax's auto-calibrated choice
                kernel_kw = {"kernel": mask_kernel} if pinned else {}
                unit, vect = masking_jax.sum_masks(
                    [s.as_bytes() for s in mask_seeds], length, config, **kernel_kw
                )
                return MaskObject(
                    MaskVect(config.vect, np.asarray(vect)),
                    MaskUnit(config.unit, unit),
                )
            except Exception:
                if self.device_sum2_strict:
                    raise
                logger.warning("device mask aggregation failed; using host path", exc_info=True)
        # mask derivations are independent per seed and the native sampler
        # releases the GIL, so they parallelize across threads
        from concurrent.futures import ThreadPoolExecutor

        mask_agg = Aggregation(config, length)
        # the validation loop below scribbles on nb_models and resets it to
        # 0; that is only correct against a freshly-built Aggregation
        assert mask_agg.nb_models == 0
        if len(mask_seeds) > 1:
            with ThreadPoolExecutor(max_workers=min(8, len(mask_seeds))) as pool:
                masks = list(pool.map(lambda s: s.derive_mask(length, config), mask_seeds))
        else:
            masks = [s.derive_mask(length, config) for s in mask_seeds]
        # replicate the incremental loop's per-mask error precedence exactly:
        # mask i is validated against the state where i models are already
        # folded, so a mismatched/invalid mask at a low index still raises
        # before a count overflow at a higher one (masking.rs check order)
        for i, mask in enumerate(masks):
            mask_agg.nb_models = i
            mask_agg.validate_aggregation(mask)
        mask_agg.nb_models = 0
        # one batched fold (native single-pass on <=2-limb configs) instead
        # of len(masks) sequential modular adds
        mask_agg.aggregate_batch(
            np.stack([m.vect.data for m in masks]),
            np.stack([m.unit.data for m in masks]),
        )
        return mask_agg.object

    # --- sending ----------------------------------------------------------

    async def _send(self, payload, next_phase: PhaseKind) -> TransitionOutcome:
        """Sign, chunk if oversized, sealed-box encrypt, POST
        (sending.rs:23-121).

        A part that fails to send is retried on later ticks (chunk-level
        retry, reference sending.rs:96-113) — already-delivered chunks are
        never re-sent; the phase only advances once every part is through.
        """
        assert self.round_params is not None
        message = Message(
            participant_pk=self.keys.public,
            coordinator_pk=self.round_params.pk,
            payload=payload,
        )
        encoder = MessageEncoder(message, self.keys.secret, self.max_message_size)
        self._pending = _PendingSend(encoder, self.round_params.pk)
        self._after_send_phase = next_phase
        return await self._drain_sends()

    async def _drain_sends(self) -> TransitionOutcome:
        assert self._pending is not None
        pending = self._pending
        while pending.next_index < pending.encoder.n_parts:
            sealed = pending.sealed_part(pending.next_index)
            try:
                await self.client.send_message(sealed)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if not _is_transient_client_error(e):
                    # a permanent rejection (4xx) will never succeed on a
                    # resend of the SAME bytes: abandon this round's send and
                    # wait for the next round instead of retrying forever
                    logger.warning(
                        "chunk send permanently rejected (part %d/%d): %s; "
                        "abandoning this round's upload",
                        pending.next_index + 1,
                        pending.encoder.n_parts,
                        e,
                    )
                    self._pending = None
                    self._after_send_phase = None
                    self.phase = PhaseKind.AWAITING
                    self.notify.idle()
                    return TransitionOutcome.COMPLETE
                logger.info(
                    "chunk send failed (part %d/%d); retrying on a later tick: %s",
                    pending.next_index + 1,
                    pending.encoder.n_parts,
                    e,
                )
                return TransitionOutcome.PENDING
            pending.next_index += 1
        self._pending = None
        if self._after_send_phase is not None:
            self.phase = self._after_send_phase
            self._after_send_phase = None
        return TransitionOutcome.COMPLETE

    # --- persistence ------------------------------------------------------

    def save(self) -> bytes:
        """Serialize the whole machine state (phase.rs:295-313)."""
        d = {
            "keys": self.keys.secret.hex(),
            "scalar": [self.scalar.numerator, self.scalar.denominator],
            "max_message_size": self.max_message_size,
            "device_sum2": self.device_sum2,
            "device_sum2_strict": self.device_sum2_strict,
            "mask_kernel": self.mask_kernel,
            "mask_seed": self.mask_seed.hex() if self.mask_seed else None,
            "phase": self.phase.value,
            "task": self.task.value,
            "sum_signature": self.sum_signature.hex() if self.sum_signature else None,
            "update_signature": self.update_signature.hex() if self.update_signature else None,
            "ephm_secret": self.ephm_keys.secret.as_bytes().hex() if self.ephm_keys else None,
            "round_params": self.round_params.to_dict() if self.round_params else None,
            # in-flight multipart send (chunk-level retry resumes exactly
            # where it stopped): ONE payload copy + cursor, not sealed parts
            "pending_send": (
                {
                    "payload": base64.b64encode(self._pending.encoder._payload_bytes).decode(),
                    "tag": int(self._pending.encoder.message.tag),
                    "message_id": getattr(self._pending.encoder, "message_id", 0),
                    "max_message_size": self._pending.encoder.max_message_size,
                    "next_index": self._pending.next_index,
                }
                if self._pending is not None
                else None
            ),
            "after_send_phase": self._after_send_phase.value if self._after_send_phase else None,
        }
        # restore() must re-derive the signing keypair, the ephemeral sum
        # keys and the injected oracle seed; the blob never leaves the
        # participant's own store (not a log/report/telemetry surface)
        return json.dumps(d).encode()  # lint: taint-ok: participant-local durable resume blob

    @classmethod
    def restore(
        cls,
        data: bytes,
        client: XaynetClient,
        model_store: ModelStore,
        notify: Optional[Notify] = None,
    ) -> "StateMachine":
        d = json.loads(data.decode())
        settings = PetSettings(
            keys=SigningKeyPair.derive_from_seed(bytes.fromhex(d["keys"])),
            scalar=Fraction(*d["scalar"]),
            max_message_size=d["max_message_size"],
            # None means "auto on device-equipped hosts" and must survive
            # the save/restore round trip
            device_sum2=(None if d.get("device_sum2") is None else bool(d["device_sum2"])),
            device_sum2_strict=bool(d.get("device_sum2_strict", False)),
            mask_kernel=str(d.get("mask_kernel") or "auto"),
            mask_seed=(
                bytes.fromhex(d["mask_seed"]) if d.get("mask_seed") else None
            ),
        )
        machine = cls(settings, client, model_store, notify)
        machine.phase = PhaseKind(d["phase"])
        machine.task = Task(d["task"])
        machine.sum_signature = bytes.fromhex(d["sum_signature"]) if d["sum_signature"] else None
        machine.update_signature = (
            bytes.fromhex(d["update_signature"]) if d["update_signature"] else None
        )
        if d["ephm_secret"]:
            machine.ephm_keys = EncryptKeyPair.derive_from_seed(bytes.fromhex(d["ephm_secret"]))
        if d["round_params"]:
            machine.round_params = RoundParameters.from_dict(d["round_params"])
        ps = d.get("pending_send")
        if ps and machine.round_params is not None:
            from ..core.message.message import Tag

            message = Message(
                participant_pk=machine.keys.public,
                coordinator_pk=machine.round_params.pk,
                payload=_RawPayload(base64.b64decode(ps["payload"])),
                tag=Tag(ps["tag"]),
            )
            encoder = MessageEncoder(
                message,
                machine.keys.secret,
                ps["max_message_size"],
                message_id=ps["message_id"],
            )
            machine._pending = _PendingSend(
                encoder, machine.round_params.pk, next_index=int(ps["next_index"])
            )
        if d.get("after_send_phase"):
            machine._after_send_phase = PhaseKind(d["after_send_phase"])
        return machine
