"""Caller-driven participant: the embeddable tick-based wrapper.

Functional port of the reference's mobile participant (reference:
rust/xaynet-mobile/src/participant.rs:129-353): the embedding application
owns the control flow and calls ``tick()``; between ticks it can inspect
``task()``, ``made_progress()``, ``should_set_model()`` and
``new_global_model()``, provide the trained model via ``set_model()``, and
suspend/resume the whole participant with ``save()`` / ``restore()``.

The reference wraps a tokio current-thread runtime; this wraps a private
asyncio event loop, so ``tick()`` is synchronous for the caller.
"""

from __future__ import annotations

import asyncio
from fractions import Fraction
from typing import Optional, Union

import numpy as np

from ..core.crypto.sign import SigningKeyPair
from .client import HttpClient, ResilientClient
from .state_machine import PetSettings, StateMachine, Task, TransitionOutcome
from .traits import ModelStore, Notify, XaynetClient


class _Events(Notify):
    def __init__(self):
        self.reset()

    def reset(self):
        self.got_new_round = False
        self.wants_model = False
        self.new_global = False

    def new_round(self):
        self.got_new_round = True

    def load_model(self):
        self.wants_model = True

    def new_model(self, model):
        self.new_global = True


def coerce_model_array(model) -> np.ndarray:
    """Staging dtype for a local model: floats go to f32; integer arrays
    keep their dtype (coercing quantized ints to f32 would corrupt values
    beyond 2^24). The float-vs-int decision against the round's mask config
    happens at mask time (`StateMachine._step_update`), where the config is
    actually known."""
    arr = np.asarray(model)
    if not np.issubdtype(arr.dtype, np.integer):
        arr = np.asarray(arr, dtype=np.float32)
    return arr


class _SettableModelStore(ModelStore):
    def __init__(self):
        self.model: Optional[np.ndarray] = None

    async def load_model(self):
        return self.model


class Participant:
    """Tick-driven PET participant."""

    def __init__(
        self,
        client: Union[str, XaynetClient],
        scalar: Fraction = Fraction(1),
        state: Optional[bytes] = None,
        keys: Optional[SigningKeyPair] = None,
        max_message_size: Optional[int] = 4096,
        # None = auto: the Sum2 device path turns on when JAX's default
        # backend is an accelerator (see PetSettings.device_sum2); an
        # explicit True forces the promoted batched pipeline at any size
        device_sum2: Optional[bool] = None,
        # Sum2 mask derive+sum route (see PetSettings.mask_kernel)
        mask_kernel: str = "auto",
        # wrap URL clients in the retrying ResilientClient (one flaky 429 or
        # dropped connection must not turn a participant into a dropout);
        # pass False to talk raw HTTP, or hand in a pre-built client
        retries: bool = True,
        # deterministic Update-task mask seed (oracle/replay only — see
        # PetSettings.mask_seed; None = the reference's random draw)
        mask_seed: Optional[bytes] = None,
    ):
        if isinstance(client, str):
            client = HttpClient(client)
            if retries:
                client = ResilientClient(client)
        self._client = client
        self._loop = asyncio.new_event_loop()
        self._events = _Events()
        self._store = _SettableModelStore()
        if state is not None:
            self._sm = StateMachine.restore(state, client, self._store, self._events)
        else:
            settings = PetSettings(
                keys=keys or SigningKeyPair.generate(),
                scalar=scalar,
                max_message_size=max_message_size,
                device_sum2=device_sum2,
                mask_kernel=mask_kernel,
                mask_seed=mask_seed,
            )
            self._sm = StateMachine(settings, client, self._store, self._events)
        self._made_progress = False

    # --- driving ----------------------------------------------------------

    def tick(self) -> None:
        """Runs one state-machine transition."""
        self._events.wants_model = False
        outcome = self._loop.run_until_complete(self._guarded_transition())
        self._made_progress = outcome == TransitionOutcome.COMPLETE

    async def _guarded_transition(self) -> TransitionOutcome:
        try:
            return await self._sm.transition()
        except Exception:
            return TransitionOutcome.PENDING

    # --- inspection -------------------------------------------------------

    def made_progress(self) -> bool:
        return self._made_progress

    def task(self) -> Task:
        return self._sm.task

    def should_set_model(self) -> bool:
        return self._events.wants_model

    def new_global_model(self) -> bool:
        """True once per round start (a fresh global model may be ready)."""
        flag = self._events.got_new_round
        self._events.got_new_round = False
        return flag

    # --- model exchange ---------------------------------------------------

    def set_model(self, model) -> None:
        self._store.model = coerce_model_array(model)

    def clear_model(self) -> None:
        """Forget the staged local model (typically at round start)."""
        self._store.model = None

    def global_model(self) -> Optional[np.ndarray]:
        return self._loop.run_until_complete(self._sm.client.get_model())

    # --- persistence ------------------------------------------------------

    def save(self) -> bytes:
        """Serializes the participant; the instance must not be used after."""
        state = self._sm.save()
        self.close()
        return state

    def close(self) -> None:
        """Releases the private event loop and any pooled transport
        connections (idempotent)."""
        # unwrap retry decorators down to the transport (keep-alive pool)
        client = getattr(self, "_client", None)
        while client is not None:
            if hasattr(client, "close"):
                try:
                    client.close()
                except Exception:
                    pass
                break
            client = getattr(client, "inner", None)
        if not self._loop.is_closed():
            self._loop.close()

    def __del__(self):  # noqa: D105 — deterministic teardown beats GC races
        try:
            self.close()
        except Exception:
            pass

    @classmethod
    def restore(cls, state: bytes, client: Union[str, XaynetClient]) -> "Participant":
        return cls(client, state=state)
