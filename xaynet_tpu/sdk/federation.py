"""LocalFederation: a one-call federated simulation harness.

Runs a coordinator (REST, background thread) and per-round role-pinned
participants driving user trainers — the pattern every simulation needs,
packaged: handle task-eligibility re-draws, round boundaries and
thread lifecycle.

    fed = LocalFederation(model_length=..., n_sum=2, n_update=6)
    trainers = [MyTrainer(shard) for shard in shards]
    for result in fed.rounds(trainers, n_rounds=3):
        print(result.round_id, result.global_model[:4])
    fed.stop()

Mind the mask config's weight bound: the default (B0) clamps weights to
|w| <= 1 — larger weights silently saturate, exactly as the protocol
specifies. Pick B2/B4/B6 (bounds 100 / 1e4 / 1e6) in
``Settings.mask.bound_type`` for bigger weight ranges.
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass
from fractions import Fraction
from typing import Iterator, Optional, Sequence

import numpy as np

from ..server.rest import RestServer
from ..server.services import Fetcher, PetMessageHandler
from ..server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from ..server.state_machine import StateMachineInitializer
from ..storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from ..storage.traits import Store
from .api import ParticipantABC
from .client import HttpClient
from .participant import Participant
from .simulation import keys_for_task


@dataclass
class RoundResult:
    round_id: int
    global_model: np.ndarray
    wall_seconds: float


class LocalFederation:
    """In-process coordinator + per-round participant management."""

    def __init__(
        self,
        model_length: int,
        n_sum: int = 1,
        n_update: int = 3,
        sum_prob: float = 0.3,
        update_prob: float = 0.6,
        phase_timeout: float = 300.0,
        settings: Optional[Settings] = None,
        device_aggregation: bool = False,
    ):
        self.n_sum, self.n_update = n_sum, n_update
        self.sum_prob, self.update_prob = sum_prob, update_prob
        if settings is None:
            settings = Settings(
                pet=PetSettings(
                    sum=PhaseSettings(
                        prob=sum_prob,
                        count=CountSettings(n_sum, n_sum),
                        time=TimeSettings(0, phase_timeout),
                    ),
                    update=PhaseSettings(
                        prob=update_prob,
                        count=CountSettings(n_update, n_update),
                        time=TimeSettings(0, phase_timeout),
                    ),
                    sum2=Sum2Settings(
                        count=CountSettings(n_sum, n_sum),
                        time=TimeSettings(0, phase_timeout),
                    ),
                )
            )
        settings.model.length = model_length
        settings.aggregation.device = device_aggregation
        self.settings = settings
        self._threads: list = []
        self._started = threading.Event()
        self.url: str = ""
        self._runner = threading.Thread(target=self._serve, daemon=True)
        self._runner.start()
        if not self._started.wait(15):
            raise RuntimeError("coordinator failed to start")
        self._probe = HttpClient(self.url)

    def _serve(self) -> None:
        async def main():
            store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
            machine, tx, events = await StateMachineInitializer(self.settings, store).init()
            rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))
            host, port = await rest.start("127.0.0.1", 0)
            self.url = f"http://{host}:{port}"
            self._loop = asyncio.get_running_loop()
            self._machine_task = asyncio.create_task(machine.run())
            self._started.set()
            try:
                await self._machine_task
            except asyncio.CancelledError:
                pass
            finally:
                await rest.stop()

        asyncio.run(main())

    def _sync(self, coro):
        return asyncio.run(coro)

    def rounds(
        self,
        trainers: Sequence[ParticipantABC],
        n_rounds: int = 1,
        round_timeout: float = 300.0,
    ) -> Iterator[RoundResult]:
        """Runs rounds; yields each new global model.

        ``trainers[:n_sum]`` back the sum participants of every round (their
        ``train_round`` is never called); the rest are cycled through the
        update slots.
        """
        if len(trainers) < self.n_sum + self.n_update:
            raise ValueError("need at least n_sum + n_update trainers")
        last_seed: Optional[bytes] = None
        for round_no in range(n_rounds):
            t0 = time.time()
            params = self._sync(self._probe.get_round_params())
            while last_seed is not None and params.seed.as_bytes() == last_seed:
                time.sleep(0.05)
                params = self._sync(self._probe.get_round_params())
            seed = params.seed.as_bytes()

            # Deterministic, single-threaded drive: fresh role-pinned
            # participants each round (participants from prior rounds are
            # dropped, so re-drawn eligibility can never steal round slots).
            members: list[tuple[Participant, ParticipantABC]] = []
            for i in range(self.n_sum):
                keys = keys_for_task(seed, self.sum_prob, self.update_prob, "sum", start=i * 1000)
                members.append((Participant(self.url, keys=keys), trainers[i]))
            for i in range(self.n_update):
                keys = keys_for_task(
                    seed, self.sum_prob, self.update_prob, "update", start=(1000 + i) * 1000
                )
                trainer = trainers[
                    self.n_sum + (round_no * self.n_update + i) % (len(trainers) - self.n_sum)
                ]
                members.append(
                    (
                        Participant(self.url, keys=keys, scalar=Fraction(1, self.n_update)),
                        trainer,
                    )
                )

            global_model = self._sync(self._probe.get_model())
            deadline = time.time() + round_timeout
            while time.time() < deadline:
                progressed = False
                for participant, trainer in members:
                    participant.tick()
                    progressed = progressed or participant.made_progress()
                    if participant.should_set_model() and trainer.participate_in_update_task():
                        training_input = (
                            trainer.deserialize_training_input(global_model)
                            if global_model is not None
                            else None
                        )
                        result = trainer.train_round(training_input)
                        participant.set_model(trainer.serialize_training_result(result))
                model = self._sync(self._probe.get_model())
                fresh = self._sync(self._probe.get_round_params())
                # the next round's parameters only appear after this round's
                # unmask published its model (identical consecutive models
                # are legal, so the model itself is no progress signal)
                if model is not None and fresh.seed.as_bytes() != seed:
                    break
                if not progressed:
                    time.sleep(0.05)
            else:
                raise TimeoutError(f"round {round_no + 1} did not complete")
            last_seed = seed
            for trainer in {id(t): t for _, t in members}.values():
                trainer.on_new_global_model(trainer.deserialize_training_input(np.asarray(model)))
            yield RoundResult(
                round_id=round_no + 1,
                global_model=np.asarray(model),
                wall_seconds=time.time() - t0,
            )

    def global_model(self) -> Optional[np.ndarray]:
        return self._sync(self._probe.get_model())

    def stop(self) -> None:
        """Stops the coordinator loop (participants are per-round, already gone)."""
        loop = getattr(self, "_loop", None)
        task = getattr(self, "_machine_task", None)
        if loop is not None and task is not None:
            loop.call_soon_threadsafe(task.cancel)
            self._runner.join(timeout=5)
        self._threads.clear()
