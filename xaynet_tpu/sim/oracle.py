"""Differential oracle: sim round vs in-process production server round.

The simulation (``sim.round``) and the production coordinator compute the
same function of (mask config, participant mask seeds, local models,
scalar): the round's unmasked global model. This module replays ONE seeded
round through both paths and asserts the results are **byte-identical**
(``float64`` buffer bytes, not approximate) — the property that turns
every future server/kernel/ops change into a checkable one: if a refactor
bends any step of the group arithmetic, the encode quantization, or the
keystream consumption, the two paths diverge and the oracle trips.

The production leg is the REAL stack — coordinator phase state machine,
PET message pipeline (sealed box, signatures, task validation, seed
dictionary), SDK participant FSMs — with only the network replaced by
in-process calls and one knob injected: each update participant's mask
seed is pinned via ``PetSettings.mask_seed`` so both legs mask with the
same seeds. The sim leg reruns the same population through the jitted
whole-round program, single-device or mesh-sharded.

Used by ``tests/test_sim_oracle.py`` (tier-1, small combos) and
``tools/sim_check.py`` (the seeded nightly sweep).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Optional

import numpy as np

from ..core.mask.config import BoundType, DataType, GroupType, MaskConfig, ModelType

SUM_PROB = 0.4
UPDATE_PROB = 0.5


class OracleMismatch(AssertionError):
    """The sim and production rounds produced different global models."""


@dataclass(frozen=True)
class OracleCase:
    """One seeded (mask config x model size x participant count) combination."""

    group_type: GroupType = GroupType.INTEGER
    data_type: DataType = DataType.F32
    bound_type: BoundType = BoundType.B0
    model_type: ModelType = ModelType.M3
    model_length: int = 13
    n_update: int = 3
    n_sum: int = 2
    seed: int = 0  # roots the weights RNG and the injected mask seeds
    block_size: int = 4  # sim participants per vmap block
    time_max: float = 60.0
    # drive the production leg's sum participants through the PROMOTED
    # device sum2 pipeline (masking_jax.sum_masks) instead of the scalar
    # host path — with a pinned route so each oracle leg is deterministic
    # about the code it exercises ("auto" = the calibrated winner). Strict:
    # a broken device kernel must trip the oracle, not hide in a fallback.
    device_sum2: bool = False
    mask_kernel: str = "auto"

    @property
    def mask_config(self) -> MaskConfig:
        return MaskConfig(self.group_type, self.data_type, self.bound_type, self.model_type)

    def describe(self) -> str:
        return (
            f"{self.group_type.name}/{self.data_type.name}/{self.bound_type.name}/"
            f"{self.model_type.name} n={self.model_length} P={self.n_update} seed={self.seed}"
        )

    def population(self) -> tuple[list[bytes], np.ndarray]:
        """The deterministic (mask seeds, local models) both legs replay."""
        rng = np.random.default_rng(self.seed)
        seeds = [rng.bytes(32) for _ in range(self.n_update)]
        weights = rng.uniform(-1, 1, (self.n_update, self.model_length)).astype(np.float32)
        return seeds, weights


@dataclass
class OracleReport:
    case: OracleCase
    identical: bool
    max_abs_diff: float
    production_sha: str
    sim_sha: str
    legs: dict = field(default_factory=dict)


async def _drive_production_round(case: OracleCase) -> np.ndarray:
    """One in-process production round with pinned mask seeds; returns the
    float64 global model exactly as the Unmask phase broadcast it."""
    from ..sdk.client import InProcessClient
    from ..sdk.simulation import keys_for_task
    from ..sdk.state_machine import PetSettings, StateMachine as ParticipantSM
    from ..sdk.traits import ModelStore
    from ..server.services import Fetcher, PetMessageHandler
    from ..server.settings import (
        CountSettings,
        PhaseSettings,
        PetSettings as ServerPet,
        Settings,
        Sum2Settings,
        TimeSettings,
    )
    from ..server.state_machine import StateMachineInitializer
    from ..storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from ..storage.traits import Store

    class _ArrayModelStore(ModelStore):
        def __init__(self, model):
            self.model = model

        async def load_model(self):
            return self.model

    settings = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=SUM_PROB,
                count=CountSettings(min=case.n_sum, max=case.n_sum),
                time=TimeSettings(min=0.0, max=case.time_max),
            ),
            update=PhaseSettings(
                prob=UPDATE_PROB,
                count=CountSettings(min=case.n_update, max=case.n_update),
                time=TimeSettings(min=0.0, max=case.time_max),
            ),
            sum2=Sum2Settings(
                count=CountSettings(min=case.n_sum, max=case.n_sum),
                time=TimeSettings(min=0.0, max=case.time_max),
            ),
        )
    )
    settings.model.length = case.model_length
    settings.mask.group_type = case.group_type
    settings.mask.data_type = case.data_type
    settings.mask.bound_type = case.bound_type
    settings.mask.model_type = case.model_type

    mask_seeds, weights = case.population()
    store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
    machine, request_tx, events = await StateMachineInitializer(settings, store).init()
    handler = PetMessageHandler(events, request_tx)
    fetcher = Fetcher(events)
    machine_task = asyncio.create_task(machine.run())
    # transition() raising is routine (a participant polling ahead of the
    # phase), so drive() retries — but the LAST error is kept: if the round
    # never completes, the cause must surface instead of an opaque timeout
    # (this oracle exists to pinpoint breakage)
    last_errors: list[BaseException] = []
    try:
        while fetcher.phase().value != "sum":
            await asyncio.sleep(0.01)
        round_seed = fetcher.round_params().seed.as_bytes()

        participants = []
        for i in range(case.n_sum):
            keys = keys_for_task(round_seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
            pet = (
                PetSettings(
                    keys=keys,
                    device_sum2=True,
                    device_sum2_strict=True,
                    mask_kernel=case.mask_kernel,
                )
                if case.device_sum2
                else PetSettings(keys=keys)
            )
            participants.append(
                ParticipantSM(
                    pet,
                    InProcessClient(fetcher, handler),
                    _ArrayModelStore(None),
                )
            )
        for i in range(case.n_update):
            keys = keys_for_task(
                round_seed, SUM_PROB, UPDATE_PROB, "update", start=(10 + i) * 1000
            )
            participants.append(
                ParticipantSM(
                    PetSettings(
                        keys=keys,
                        scalar=Fraction(1, case.n_update),
                        mask_seed=mask_seeds[i],
                    ),
                    InProcessClient(fetcher, handler),
                    _ArrayModelStore(weights[i]),
                )
            )

        async def drive(sm):
            for _ in range(1000):
                try:
                    await sm.transition()
                except asyncio.CancelledError:
                    raise
                except Exception as err:
                    last_errors.append(err)
                if fetcher.model() is not None and sm.phase.value == "awaiting":
                    return
                await asyncio.sleep(0.01)

        await asyncio.gather(*(drive(p) for p in participants))
        while fetcher.model() is None:
            await asyncio.sleep(0.01)
        return np.asarray(fetcher.model(), dtype=np.float64)
    except asyncio.CancelledError:
        if fetcher.model() is None and last_errors:
            raise RuntimeError(
                f"production round never completed; last participant error: "
                f"{type(last_errors[-1]).__name__}: {last_errors[-1]}"
            ) from last_errors[-1]
        raise
    finally:
        machine_task.cancel()
        try:
            await machine_task
        except (asyncio.CancelledError, Exception):  # lint: swallow-ok (teardown)
            pass


def run_production_round(case: OracleCase, timeout: float = 120.0) -> np.ndarray:
    """Synchronous wrapper around the in-process production round."""
    return asyncio.run(asyncio.wait_for(_drive_production_round(case), timeout=timeout))


def run_sim_round(case: OracleCase, mesh=None):
    """The same population through the jitted whole-round program."""
    from .round import SimRound, SimSpec

    seeds, weights = case.population()
    spec = SimSpec(
        config=case.mask_config.pair(),
        model_length=case.model_length,
        block_size=case.block_size,
    )
    sim = SimRound(spec, mesh=mesh)
    return sim.run(seeds, weights, scalar=Fraction(1, case.n_update))


def run_oracle_case(
    case: OracleCase,
    mesh=None,
    production_model: Optional[np.ndarray] = None,
    timeout: float = 120.0,
) -> OracleReport:
    """Replay ``case`` through both paths; raise ``OracleMismatch`` unless
    the global models are byte-identical.

    ``production_model`` short-circuits the (slow) server leg so several
    sim variants (single-device, mesh, block sizes) can be checked against
    one production run.
    """
    import hashlib

    if production_model is None:
        production_model = run_production_round(case, timeout=timeout)
    sim_result = run_sim_round(case, mesh=mesh)
    prod = np.asarray(production_model, dtype=np.float64)
    simm = np.asarray(sim_result.global_model, dtype=np.float64)
    p_sha = hashlib.sha256(prod.tobytes()).hexdigest()
    s_sha = hashlib.sha256(simm.tobytes()).hexdigest()
    identical = prod.shape == simm.shape and prod.tobytes() == simm.tobytes()
    max_diff = float(np.max(np.abs(prod - simm))) if prod.shape == simm.shape else float("inf")
    report = OracleReport(
        case=case,
        identical=identical,
        max_abs_diff=max_diff,
        production_sha=p_sha,
        sim_sha=s_sha,
        legs={
            "mesh": None if mesh is None else len(mesh.devices.flat),
            "nb_models": sim_result.nb_models,
        },
    )
    if not identical:
        raise OracleMismatch(
            f"sim diverged from production for {case.describe()}: "
            f"sha {s_sha[:16]} != {p_sha[:16]}, max |diff| {max_diff:.3e}"
        )
    return report
