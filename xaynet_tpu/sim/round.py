"""A whole PET round as one jittable JAX program.

The production stack runs a round as a socketed conversation: participants
mask locally, the coordinator folds masked updates as they arrive, sum
participants reconstruct the aggregate mask from the seed dictionary, and
the Unmask phase subtracts and decodes. Every step of that conversation is
deterministic given (mask config, participant seeds, local models, scalar)
— so the round is equally expressible as a pure function, which is what
``SimRound`` builds (the DrJAX observation applied to PET):

    phase 1 (update):  vmap over participants of
                       ``derive_mask_ingraph`` + modular add of the
                       fixed-point-encoded model  -> masked models
    phase 2 (fold):    modular tree-sum of the masked population,
                       scanned over participant blocks (and sharded
                       over the mesh's participant axis when present)
    phase 3 (sum2):    the sum mask — the modular sum of every
                       participant's mask — reconstructed in-graph
    phase 4 (unmask):  modular subtract, still in-graph

All four phases trace into ONE ``jax.jit`` program over ``uint32`` limb
tensors: exact group arithmetic, no float in the graph, no host syncs, no
Python-level per-participant loop. The float boundary — fixed-point encode
of the local models before the program, fixed-point decode of the unmasked
aggregate after it — runs through the SAME production host functions
(``core/mask/encode.py``) a real participant and the real Unmask phase
use, which is what makes the simulated global model byte-identical to the
production server round (asserted by ``sim.oracle``).

Scaling knobs: ``block_size`` bounds how many participants derive
concurrently (device memory ~ block_size x keystream chunk); blocks fold
sequentially under ``lax.scan``; a multi-device mesh shards whole blocks
across its devices (the PR-7 shard-plan idiom turned 90 degrees: the
production fold shards the *model* axis because updates arrive serially —
the simulation owns all participants up front, so it shards the
*participant* axis and modularly combines the per-device partial
aggregates, which is exact because masked aggregation is a commutative
modular sum).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.mask.config import MaskConfigPair
from ..core.mask.encode import (
    clamp_scalar,
    decode_scalar_sum,
    decode_vect_any,
    decode_vect_fast,
    encode_unit,
    has_fast_path,
)
from ..ops import limbs as host_limbs, limbs_jax
from ..ops.masking_jax import (
    derive_chunk_budgets,
    derive_mask_ingraph,
    encode_models_batch,
    seed_words,
)
from ..parallel.mesh import MODEL_AXIS, shard_map_compat
from ..telemetry import profiling


def seeds_for(n: int, root: int = 0) -> list[bytes]:
    """``n`` deterministic 32-byte mask seeds (research-workload helper)."""
    rng = np.random.default_rng(root)
    return [rng.bytes(32) for _ in range(n)]


@dataclass(frozen=True)
class SimSpec:
    """Static shape of a simulated round (hashable: one compiled program each)."""

    config: MaskConfigPair
    model_length: int
    block_size: int = 128  # participants deriving concurrently per vmap block
    fuse_mask_sum: bool = True  # derive once, feed update fold AND sum-mask fold
    return_internals: bool = False  # also return the pre-unmask aggregates

    def __post_init__(self):
        if self.model_length < 1:
            raise ValueError("model_length must be >= 1")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")


@dataclass
class SimResult:
    """Outcome of one simulated round."""

    global_model: np.ndarray  # float64[model_length], the unmasked aggregate
    nb_models: int
    scalar_sum: Fraction
    model_vect_limbs: np.ndarray  # uint32[model_length, L] — unmasked group elements
    model_unit_int: int
    internals: Optional[dict] = field(default=None, repr=False)


class SimRound:
    """One compiled whole-round program for a fixed (spec, mesh).

    ``run(seeds, weights, scalar)`` simulates the round for any population
    size (padded up to the compiled block grid); population shapes are
    static per spec, so successive runs reuse the compiled program.
    """

    def __init__(self, spec: SimSpec, mesh=None):
        self.spec = spec
        self.mesh = mesh if mesh is not None and len(mesh.devices.flat) > 1 else None
        cfg = spec.config
        self._ol_v = tuple(int(x) for x in host_limbs.order_limbs_for(cfg.vect.order))
        self._ol_u = tuple(int(x) for x in host_limbs.order_limbs_for(cfg.unit.order))
        self._n_limb_v = host_limbs.n_limbs_for_order(cfg.vect.order)
        self._n_limb_u = host_limbs.n_limbs_for_order(cfg.unit.order)
        # chunk budgets: block_size lanes derive concurrently (scan blocks
        # are sequential; each mesh device runs block_size lanes too) — the
        # shared provisioning rule of the promoted production derive
        self._unit_chunk, self._vect_chunk = derive_chunk_budgets(
            spec.model_length, cfg, spec.block_size
        )
        self._program = jax.jit(self._build_program())
        self.program_calls = 0  # observability: one per run(), never per participant

    # --- in-graph program bodies (host syncs forbidden, see tools/lint.py) --

    def _build_program(self):
        spec, mesh = self.spec, self.mesh
        n = spec.model_length
        ol_v, ol_u = np.asarray(self._ol_v, np.uint32), np.asarray(self._ol_u, np.uint32)
        unit_chunk, vect_chunk = self._unit_chunk, self._vect_chunk
        config = spec.config
        zero_carry = self._zero_carry

        def _prog_derive(kw):
            return derive_mask_ingraph(kw, n, config, unit_chunk, vect_chunk)

        def _prog_update_fold(carry, xs):
            """One participant block: derive masks, mask the encoded models,
            fold the masked population (and, when ``fuse_mask_sum``, the
            mask sum in the same pass — phases 1+2+3)."""
            acc_mv, acc_mu, acc_kv, acc_ku = carry
            kw, enc, unit_enc, valid = xs
            units, vects = jax.vmap(_prog_derive)(kw)  # [B, L1], [B, n, L]
            masked = limbs_jax.mod_add(enc, vects, ol_v)
            unit_masked = limbs_jax.mod_add(unit_enc, units, ol_u)
            # padding lanes contribute the group identity (zero) everywhere
            masked = jnp.where(valid[:, None, None], masked, jnp.uint32(0))
            unit_masked = jnp.where(valid[:, None], unit_masked, jnp.uint32(0))
            acc_mv = limbs_jax.mod_add(acc_mv, limbs_jax.batch_mod_sum(masked, ol_v), ol_v)
            acc_mu = limbs_jax.mod_add(
                acc_mu[None, :], limbs_jax.batch_mod_sum(unit_masked[:, None, :], ol_u), ol_u
            )[0]
            if spec.fuse_mask_sum:
                vects = jnp.where(valid[:, None, None], vects, jnp.uint32(0))
                units = jnp.where(valid[:, None], units, jnp.uint32(0))
                acc_kv = limbs_jax.mod_add(acc_kv, limbs_jax.batch_mod_sum(vects, ol_v), ol_v)
                acc_ku = limbs_jax.mod_add(
                    acc_ku[None, :], limbs_jax.batch_mod_sum(units[:, None, :], ol_u), ol_u
                )[0]
            return (acc_mv, acc_mu, acc_kv, acc_ku), None

        def _prog_mask_sum_fold(carry, xs):
            """Phase 3 standalone (``fuse_mask_sum=False``): the sum
            participants' reconstruction re-derives every mask from the
            seed dictionary, exactly like a real Sum2 leg."""
            acc_kv, acc_ku = carry
            kw, valid = xs
            units, vects = jax.vmap(_prog_derive)(kw)
            vects = jnp.where(valid[:, None, None], vects, jnp.uint32(0))
            units = jnp.where(valid[:, None], units, jnp.uint32(0))
            acc_kv = limbs_jax.mod_add(acc_kv, limbs_jax.batch_mod_sum(vects, ol_v), ol_v)
            acc_ku = limbs_jax.mod_add(
                acc_ku[None, :], limbs_jax.batch_mod_sum(units[:, None, :], ol_u), ol_u
            )[0]
            return (acc_kv, acc_ku), None

        def _prog_shard(kw, enc, unit_enc, valid):
            """Per-device slice of the block grid: scan the local blocks,
            return partial accumulators with a leading singleton axis so
            shard_map concatenates them into ``[ndev, ...]`` partials."""
            unit_b = jnp.broadcast_to(unit_enc, kw.shape[:2] + unit_enc.shape[-1:])
            (mv, mu, kv, ku), _ = jax.lax.scan(
                _prog_update_fold, zero_carry(), (kw, enc, unit_b, valid)
            )
            if not spec.fuse_mask_sum:
                zeros = zero_carry()
                (kv, ku), _ = jax.lax.scan(_prog_mask_sum_fold, (zeros[2], zeros[3]), (kw, valid))
            return mv[None], mu[None], kv[None], ku[None]

        def _prog_round(kw, enc, unit_enc, valid):
            """The whole round. Inputs: ``kw`` uint32[nblocks, B, 8] seed
            words, ``enc`` uint32[nblocks, B, n, L] encoded models,
            ``unit_enc`` uint32[L1], ``valid`` bool[nblocks, B]."""
            if mesh is None:
                mv, mu, kv, ku = _prog_shard(kw, enc, unit_enc, valid)
                mv, mu, kv, ku = mv[0], mu[0], kv[0], ku[0]
            else:
                from jax.sharding import PartitionSpec as P

                sharded = shard_map_compat(
                    _prog_shard,
                    mesh,
                    in_specs=(P(MODEL_AXIS), P(MODEL_AXIS), P(), P(MODEL_AXIS)),
                    out_specs=(P(MODEL_AXIS), P(MODEL_AXIS), P(MODEL_AXIS), P(MODEL_AXIS)),
                )
                pmv, pmu, pkv, pku = sharded(kw, enc, unit_enc, valid)
                # cross-device combine: modular sums are associative and
                # commutative, so folding per-device partials is exact
                mv = limbs_jax.batch_mod_sum(pmv, ol_v)
                mu = limbs_jax.batch_mod_sum(pmu[:, None, :], ol_u)[0]
                kv = limbs_jax.batch_mod_sum(pkv, ol_v)
                ku = limbs_jax.batch_mod_sum(pku[:, None, :], ol_u)[0]
            # phase 4: unmask — subtract the reconstructed sum mask
            model_v = limbs_jax.mod_sub(mv, kv, ol_v)
            model_u = limbs_jax.mod_sub(mu[None, :], ku[None, :], ol_u)[0]
            if spec.return_internals:
                return model_v, model_u, (mv, mu, kv, ku)
            return model_v, model_u, None

        return _prog_round

    def _zero_carry(self):
        n = self.spec.model_length
        return (
            jnp.zeros((n, self._n_limb_v), dtype=jnp.uint32),
            jnp.zeros((self._n_limb_u,), dtype=jnp.uint32),
            jnp.zeros((n, self._n_limb_v), dtype=jnp.uint32),
            jnp.zeros((self._n_limb_u,), dtype=jnp.uint32),
        )

    # --- host boundary ----------------------------------------------------

    def _grid(self, n_participants: int) -> tuple[int, int]:
        """(nblocks, padded population) for this spec/mesh."""
        block = self.spec.block_size
        n_dev = 1 if self.mesh is None else len(self.mesh.devices.flat)
        stride = block * n_dev
        padded = -(-n_participants // stride) * stride
        return padded // block, padded

    def run(
        self,
        seeds: list[bytes] | np.ndarray,
        weights: np.ndarray,
        scalar: Fraction = Fraction(1),
    ) -> SimResult:
        """Simulate one round: ``seeds`` are the participants' mask seeds
        (list of 32-byte strings or ``uint32[P, 8]`` key words), ``weights``
        the ``[P, model_length]`` local models, ``scalar`` the shared
        update scalar (the homogeneous-population shape; the production
        analogue is every participant sending ``scalar=1/P``)."""
        spec = self.spec
        if isinstance(seeds, np.ndarray):
            kw = np.asarray(seeds, dtype=np.uint32)
        else:
            kw = seed_words(list(seeds))
        if kw.ndim != 2 or kw.shape[1] != 8:
            raise ValueError("seeds must be 32-byte strings or uint32[P, 8] key words")
        p = kw.shape[0]
        if p < 1:
            raise ValueError("need at least one participant")
        cfg = spec.config
        if p > min(cfg.vect.max_nb_models, cfg.unit.max_nb_models):
            raise ValueError("TooManyModels: population exceeds the config's max_nb_models")
        weights = np.asarray(weights)
        if weights.shape != (p, spec.model_length):
            raise ValueError(f"weights must be [{p}, {spec.model_length}], got {weights.shape}")

        # float -> group boundary: the production fixed-point encode,
        # vectorized once over the whole population
        unit_enc, enc = encode_models_batch(weights, scalar, cfg)

        nblocks, padded = self._grid(p)
        if padded != p:
            kw = np.concatenate([kw, np.zeros((padded - p, 8), np.uint32)])
            enc = np.concatenate([enc, np.zeros((padded - p, *enc.shape[1:]), np.uint32)])
        valid = np.arange(padded) < p
        shape_b = (nblocks, spec.block_size)

        model_v, model_u, internals = profiling.timed_kernel(
            "sim_round",
            p * spec.model_length,
            lambda: self._program(
                jnp.asarray(kw.reshape(*shape_b, 8)),
                jnp.asarray(enc.reshape(*shape_b, *enc.shape[1:])),
                jnp.asarray(unit_enc),
                jnp.asarray(valid.reshape(shape_b)),
            ),
        )
        self.program_calls += 1

        # group -> float boundary: the production unmask decode
        n_vect = np.asarray(model_v)  # lint: sync-ok (host decode boundary)
        unit_int = host_limbs.limbs_to_int(np.asarray(model_u))  # lint: sync-ok
        scalar_sum = decode_scalar_sum(unit_int, cfg.unit, p)
        # unit-channel integrity: the unmasked unit must decode to exactly
        # P quantized clamped scalars (quantization per the fixed-point
        # encode, identical to what P production participants submit)
        s_clamped = clamp_scalar(scalar, cfg.unit)
        expect = decode_scalar_sum(p * encode_unit(s_clamped, cfg.unit), cfg.unit, p)
        if scalar_sum != expect:
            raise AssertionError(
                f"unit channel corrupted: decoded scalar sum {scalar_sum} != {expect}"
            )
        if has_fast_path(cfg.vect):
            global_model = decode_vect_fast(n_vect, cfg.vect, p, scalar_sum)
        else:
            global_model = decode_vect_any(n_vect, cfg.vect, p, scalar_sum)

        out_internals = None
        if internals is not None:
            mv, mu, kv, ku = internals
            out_internals = {
                "masked_vect_sum": np.asarray(mv),  # lint: sync-ok
                "masked_unit_sum": np.asarray(mu),  # lint: sync-ok
                "mask_vect_sum": np.asarray(kv),  # lint: sync-ok
                "mask_unit_sum": np.asarray(ku),  # lint: sync-ok
            }
        return SimResult(
            global_model=np.asarray(global_model, dtype=np.float64),
            nb_models=p,
            scalar_sum=scalar_sum,
            model_vect_limbs=n_vect,
            model_unit_int=unit_int,
            internals=out_internals,
        )
