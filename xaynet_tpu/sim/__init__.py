"""In-graph federated PET simulation (DrJAX-style whole-round programs).

``SimRound`` expresses an entire PET round — per-participant mask
derivation, masked-model generation, sharded modular aggregation, sum-mask
reconstruction, unmask — as ONE vmapped/jitted JAX program with no server,
sockets, or Python loop between phases. Two payoffs:

- a research workload: simulate thousands of participants per second on a
  single device (or a mesh) without a coordinator process;
- a differential oracle (``sim.oracle``): the same seeds driven through the
  in-process production server path must produce a byte-identical global
  model, turning every future server/kernel change into a
  property-checkable one.

See docs/DESIGN.md §13.
"""

from .round import SimResult, SimRound, SimSpec, seeds_for
from .oracle import OracleCase, OracleMismatch, run_oracle_case, run_production_round

__all__ = [
    "SimResult",
    "SimRound",
    "SimSpec",
    "seeds_for",
    "OracleCase",
    "OracleMismatch",
    "run_oracle_case",
    "run_production_round",
]
