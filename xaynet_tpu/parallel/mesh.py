"""Device meshes and shardings for the aggregation buffers.

The reference scales by a single-threaded bignum loop on one CPU core; the
TPU-native design shards the ``uint32[model_len, L]`` aggregation buffer over
the model-length axis of a 1-D device mesh (``NamedSharding``). Modular
aggregation and unmasking are purely elementwise over that axis, so the
sharded kernels run with zero collectives — each device owns a contiguous
slice of the model and the full round needs only the initial host->device
scatter and the final gather. Multi-host pods extend the same mesh over
ICI/DCN without code changes (jax.sharding handles placement).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it at top level with ``check_vma``; 0.4.x ships it in
    ``jax.experimental.shard_map`` with the equivalent ``check_rep`` knob
    (pallas_call's out_shape carries no vma/rep either way, so the check is
    disabled in both). The ONE shim for every shard_map call site
    (parallel/aggregator.py, sim/round.py) — the API moved once already,
    and the next move must be absorbed in one place.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    return _exp_shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def make_mesh(devices=None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, named for the model axis."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (MODEL_AXIS,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, k: int) -> int:
    """Model length padded so every device holds an equal slice."""
    return -(-n // k) * k


def shard_slices(padded_len: int, n_dev: int) -> list[tuple[int, int]]:
    """The contiguous model-axis column slice ``[lo, hi)`` each mesh device
    owns under the 1-D ``P(None, MODEL_AXIS)`` sharding, in mesh-device
    order. ``padded_len`` must already be a multiple of ``n_dev``
    (``pad_to_multiple`` guarantees it), so the slices are equal-width and
    the device-d slice of a serialized wire block is element-aligned."""
    if padded_len % n_dev:
        raise ValueError("padded length must divide evenly across devices")
    width = padded_len // n_dev
    return [(d * width, (d + 1) * width) for d in range(n_dev)]
