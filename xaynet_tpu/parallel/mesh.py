"""Device meshes and shardings for the aggregation buffers.

The reference scales by a single-threaded bignum loop on one CPU core; the
TPU-native design shards the ``uint32[model_len, L]`` aggregation buffer over
the model-length axis of a 1-D device mesh (``NamedSharding``). Modular
aggregation and unmasking are purely elementwise over that axis, so the
sharded kernels run with zero collectives — each device owns a contiguous
slice of the model and the full round needs only the initial host->device
scatter and the final gather. Multi-host pods extend the same mesh over
ICI/DCN without code changes (jax.sharding handles placement).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"


def make_mesh(devices=None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, named for the model axis."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (MODEL_AXIS,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, k: int) -> int:
    """Model length padded so every device holds an equal slice."""
    return -(-n // k) * k
