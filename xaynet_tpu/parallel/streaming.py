"""Streaming aggregation: bounded producer/consumer over the sharded fold.

``ShardedAggregator``'s batch entry points serialize the three legs of every
fold — host staging (pad + transpose + ``device_put``), the fold dispatch,
and (on the wire path) a blocking acceptance-vector fetch — so the host and
the device take turns idling. This module turns that into a pipeline:

- **staging buffer ring** — a small set of pre-allocated host buffers;
  batch N+1 is padded/copied into a ring buffer while batch N folds, and
  the per-batch ``np.pad``/``np.stack`` allocations (plus their page-fault
  tax, ~0.15 s per 200 MB at 25M params) disappear entirely. A buffer is
  reused only after the fold that consumed it has finished reading host
  memory (for device kernels: after the ``device_put`` transfer is
  complete; for the native host kernel: after the fold call returns).
- **dispatch-ahead depth** — up to ``dispatch_ahead`` batches are queued to
  a single fold worker thread, so XLA's asynchronous dispatch keeps
  multiple folds in flight behind one another while the producer stages
  ahead (DrJAX-style MapReduce pipelining, arxiv 2403.07128).
- **deferred acceptance syncs** — wire batches collect their ``ok`` arrays
  as in-flight device values; ``drain()`` fetches them all in ONE sync at
  flush/phase end instead of one blocking ``np.asarray(ok)`` per batch.
  Per-member accept/reject semantics and ``nb_models`` are byte-identical
  to the sequential path — invalid updates are zeroed inside the fold
  either way, and the deferred fetch only moves *when* the host learns the
  verdict, never what it is.

Fold order is FIFO (single worker), and the lazy-carry fold is an exact
modular sum, so the aggregate is byte-identical to sequential
``add_batch``/``add_wire_batch`` calls over the same updates regardless of
how far the pipeline runs ahead.

**Shard-parallel mode (multi-device meshes).** On a mesh of D devices the
pipeline runs ONE FOLD WORKER PER SHARD instead of the single FIFO worker:
each mesh device owns its contiguous model-axis plane slice with a donated
per-shard accumulator (``shards.ShardPlan``), the producer slices the
padded batch ONCE on the host into per-shard staging rings, and each
shard's host→device transfer overlaps the other shards' in-flight folds
(device kernels) or each shard's threaded host fold runs concurrently
under a split thread budget (the native kernel). A batch COMMITS — counts
toward ``nb_models`` / leaves flight — only when EVERY shard folded its
slice (``_BatchJob``), so per-shard progress skew never shows up in the
accounting; ``drain()`` is the cross-shard barrier that performs the one
deferred acceptance sync and reassembles the per-shard accumulators into
the aggregator's global ``acc``. Wire batches keep their single
mesh-program unpack (the psum-consistent validity mask of the sequential
path) and fan only the FOLD out per shard, so acceptance semantics are
byte-identical to ``add_wire_batch``. The degradation ladder is per-shard:
a shard's fold failure with a provably untouched shard accumulator retries
once synchronously on that shard alone (the other shards' slices of the
batch fold normally — consistency comes from the commit barrier), flips
the whole pipeline to the synchronous path on success, and poisons it
permanently on a second failure.

**Degradation ladder (streaming -> sync -> fail).** A fold failure in the
worker does NOT immediately poison the round: the accumulator is only
reassigned after a fold returns, so the failed batch is retried once
*synchronously*; on success the pipeline switches to the synchronous fold
path for the rest of the round (submits fold on the caller's thread,
logged + ``xaynet_streaming_degraded``) — the round completes with the
exact same aggregate, just without overlap. Only when the synchronous
retry ALSO fails is the pipeline poisoned — permanently, because the
batch's updates are lost and the accumulator no longer corresponds to any
consistent update set. Every poisoned-pipeline error names the poisoning
batch index and the original exception. Failures surfacing at ``drain()``
(XLA's asynchronous dispatch) skip the retry: the accumulator may already
reference the failed computation, so no consistent retry exists.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
import weakref

import numpy as np

from ..ops.fold_jax import MAX_LAZY_BATCH
from ..resilience.faults import maybe_fail
from ..telemetry import profiling
from ..telemetry import tracing as trace
from ..telemetry.recorder import flight_dump
from ..telemetry.registry import get_registry
from ..tenancy.pool import get_pool
from ..tenancy.scheduler import get_scheduler
# BYTES_STAGED: one module owns the xaynet_bytes_staged_total family —
# aggregator.py registers it (wire-ingest staging accounts there too) and
# the streaming rings account through the shared symbol
from .aggregator import BYTES_REDUCED, BYTES_STAGED, ShardedAggregator

logger = logging.getLogger(__name__)

SPAN_STAGE = trace.declare_span("stream.stage")
SPAN_FOLD = trace.declare_span("stream.fold")
SPAN_COMMIT = trace.declare_span("stream.commit")
SPAN_DRAIN = trace.declare_span("stream.drain")
SPAN_EAGER_UNMASK = trace.declare_span("overlap.eager_unmask")

_registry = get_registry()
STAGING_DEPTH = _registry.gauge(
    "xaynet_streaming_staging_depth",
    "Staging ring buffers currently owned by in-flight batches.",
)
INFLIGHT_FOLDS = _registry.gauge(
    "xaynet_streaming_inflight_folds",
    "Fold batches submitted to the streaming pipeline and not yet folded.",
)
OVERLAP_RATIO = _registry.gauge(
    "xaynet_streaming_overlap_ratio",
    "Fraction of the shorter pipeline leg (staging vs folding) that ran "
    "concurrently with the other leg during the last drain window "
    "(1 = perfect overlap, 0 = fully serialized).",
)
BATCHES_TOTAL = _registry.counter(
    "xaynet_streaming_batches_total",
    "Streaming pipeline batches, by stage (staged = submitted, "
    "folded = fold completed).",
    ("stage",),
)
DEGRADED = _registry.gauge(
    "xaynet_streaming_degraded",
    "1 while the streaming pipeline has degraded to the synchronous fold "
    "path after a fold failure (resets with the next pipeline).",
)
DEGRADATIONS = _registry.counter(
    "xaynet_streaming_degradations_total",
    "Times a streaming pipeline degraded to the synchronous fold path.",
)
SHARD_STAGING_DEPTH = _registry.gauge(
    "xaynet_streaming_shard_staging_depth",
    "Per-shard staging ring buffers currently owned by in-flight batches "
    "(shard-parallel pipelines).",
    ("shard",),
)
SHARD_INFLIGHT = _registry.gauge(
    "xaynet_streaming_shard_inflight_folds",
    "Per-shard fold items queued to or executing in the shard's worker.",
    ("shard",),
)
SHARD_OVERLAP = _registry.gauge(
    "xaynet_streaming_shard_overlap_ratio",
    "Per-shard fraction of the shorter pipeline leg (staging vs folding) "
    "that ran concurrently with the other leg during the last drain window.",
    ("shard",),
)
_SHUTDOWN = object()


class StreamingError(RuntimeError):
    """The fold pipeline failed; the aggregate is unusable."""


class _UnsafeFoldError(Exception):
    """A fold failed at a point where the accumulator may already have been
    reassigned (post-dispatch transfer wait / acceptance fetch): no
    consistent synchronous retry exists, the pipeline must poison.
    ``__cause__`` is the real failure. ``settled`` is True when the batch's
    in-flight count was already handed off (planar ``_credit`` ran) so the
    poison handler must not subtract it again."""

    def __init__(self, settled: bool = False):
        super().__init__()
        self.settled = settled


class StreamTicket:
    """Handle for one submitted batch.

    ``accepted`` resolves at the next ``drain()``: a ``bool[K]`` per-member
    acceptance vector for wire batches, all-True for pre-validated planar
    batches. (In degraded/sync mode it resolves at submit time.)
    """

    __slots__ = ("k", "accepted", "_ok")

    def __init__(self, k: int):
        self.k = k
        self.accepted: np.ndarray | None = None
        self._ok = None  # in-flight device acceptance vector (wire batches)


class _BatchJob:
    """Cross-shard accounting for ONE batch in shard-parallel mode.

    Each of the D shard workers folds its slice independently; the batch
    COMMITS — ``nb_models`` credit for planar batches, ring-buffer release
    for the shared wire buffer, the folded/failed metric — only when the
    LAST shard finishes (``remaining`` hits zero under the pipeline lock).
    ``failed`` is sticky: one shard's loss fails the whole batch, because a
    batch folded on some shards but not others corresponds to no
    consistent update set (the pipeline is poisoned by then anyway).
    """

    __slots__ = ("kind", "k", "ticket", "seq", "remaining", "failed", "retried",
                 "staged", "global_release")

    def __init__(self, kind: str, k: int, ticket, seq: int, n_shards: int):
        self.kind = kind
        self.k = k
        self.ticket = ticket
        self.seq = seq
        self.remaining = n_shards  # guarded-by: _lock (the owning pipeline's)
        self.failed = False  # guarded-by: _lock
        self.retried = False  # guarded-by: _lock
        # staged/global_release are NOT lock-guarded: after `remaining`
        # hits zero under the lock, exactly ONE worker (the last shard)
        # reaches the commit tail that touches them — ownership handoff
        # through the counter, not mutual exclusion
        self.staged = None  # wire: the mesh-staged byte array (transfer barrier)
        self.global_release = None  # wire: (ring, buf) released at commit


class _UnmaskJob:
    """One eager per-shard unmask pass riding the shard queues
    (docs/DESIGN.md §22): each shard worker subtracts ITS mask slice
    against its own accumulator buffer as soon as the shard's last queued
    fold commits (queue FIFO is the ordering guarantee — the unmask item
    sits behind every fold item of the round). Workers write disjoint row
    ranges of ``out``; ``error`` is first-failure sticky and the caller
    falls back to the drain-time unmask pass (the subtract is functional —
    a failed shard leaves its accumulator untouched)."""

    __slots__ = ("mask_planar", "out", "remaining", "error", "done")

    def __init__(self, mask_planar, out, n_shards: int):
        self.mask_planar = mask_planar
        self.out = out
        self.remaining = n_shards  # guarded-by: _lock (the owning pipeline's)
        self.error = None  # guarded-by: _lock
        self.done = threading.Event()


def _release_ring_leases(pool, leases: list) -> None:
    """Module-level so a ring's GC finalizer holds no ring reference."""
    for lease in leases:
        pool.release(lease)


def _ring_migrator(view) -> None:
    """Compaction swap hook for a QUIESCENT ring lease (docs/DESIGN.md
    §23): the pool already rewrote ``lease.array`` to the migrated view
    under its lock, and the ring's free queue holds the lease object —
    not the stale array — so there is no ring state left to fix up.
    Module-level so a lease never strongly references its ring (the GC
    finalizer backstop must still fire for abandoned pipelines)."""


class _StagingRing:
    """Fixed pool of pre-allocated host staging buffers.

    ``acquire`` blocks while every buffer is owned by an in-flight batch —
    this is the pipeline's memory bound (the producer can run at most
    ``size`` batches ahead of the fold worker).

    Buffers are page runs LEASED from the shared accumulator pool
    (``tenancy.pool``) under the ring's tenant — staging planes (packed
    byte-planar included) page exactly like the shard accumulators, so
    concurrent tenants' rings pack into one arena. ``close()`` releases
    the leases; a GC finalizer backstops abandoned pipelines.

    Free buffers opt into pool compaction (§23): while a lease sits in
    the free queue it carries a migrator, so another tenant's
    between-round defrag may slide it; ``acquire`` clears the migrator
    through the pool lock BEFORE reading the array, making every
    in-flight buffer an immovable barrier, and ``release`` re-registers
    it on the way back in.
    """

    def __init__(self, size: int, shape: tuple, dtype, gauge=None,
                 pool=None, tenant: str = "default"):
        self._free: queue_mod.Queue = queue_mod.Queue()
        self.size = size
        # per-shard rings report on the shard-labelled gauge; the global
        # depth gauge keeps counting every owned buffer either way
        self._gauge = gauge
        self._pool = pool if pool is not None else get_pool()
        self._leases = [self._pool.lease_host(tenant, shape, dtype) for _ in range(size)]
        self._inflight: dict[int, object] = {}  # id(view) -> lease, checked-out buffers
        for lease in self._leases:
            self._pool.set_migrator(lease, _ring_migrator)
            self._free.put(lease)
        # abandoned pipelines (dropped without close()) give their pages
        # back when the ring is collected — by then nothing can alias them
        weakref.finalize(self, _release_ring_leases, self._pool, self._leases)

    def close(self) -> None:
        """Release the ring's page leases (idempotent; the buffers must no
        longer be in flight — the pipeline drains before closing)."""
        _release_ring_leases(self._pool, self._leases)

    def acquire(self, timeout: float | None = None) -> np.ndarray:
        lease = self._free.get(timeout=timeout)
        # pin first, read second: set_migrator takes the pool lock, so a
        # compaction mid-flight either finished (lease.array is the new
        # view) or will now skip this lease entirely
        self._pool.set_migrator(lease, None)
        buf = lease.array
        self._inflight[id(buf)] = lease
        STAGING_DEPTH.inc()
        if self._gauge is not None:
            self._gauge.inc()
        return buf

    def release(self, buf: np.ndarray) -> None:
        STAGING_DEPTH.dec()
        if self._gauge is not None:
            self._gauge.dec()
        lease = self._inflight.pop(id(buf), None)
        if lease is None:
            return  # close() raced a late release; the lease is gone
        self._pool.set_migrator(lease, _ring_migrator)
        self._free.put(lease)


def _worker_main(ref: "weakref.ref[StreamingAggregator]", q: queue_mod.Queue) -> None:
    """Fold worker loop. Holds NO strong reference to the pipeline between
    items: an abandoned pipeline (e.g. a round that died before drain) is
    garbage-collected normally, and its ``weakref.finalize`` wakes this
    thread with the shutdown sentinel so it exits instead of leaking."""
    while True:
        item = q.get()
        try:
            if item is _SHUTDOWN:
                return
            self = ref()
            if self is None:
                return
            self._process(item)
            del self
        finally:
            q.task_done()


class StreamingAggregator:
    """Bounded streaming front-end over a :class:`ShardedAggregator`.

    One fold worker consumes staged batches FIFO; the caller's thread only
    stages. ``submit_*`` may block — on the staging ring when the producer
    is ``staging_buffers`` batches ahead, on the dispatch queue when it is
    ``dispatch_ahead`` folds ahead — which is the pipeline's backpressure.
    ``drain()`` waits for in-flight work, performs the one deferred
    acceptance sync, credits ``nb_models`` for wire batches, and publishes
    the overlap ratio.

    NOT thread-safe for concurrent producers: submits must come from one
    thread at a time (the coordinator's executor serializes them; tests and
    the bench are single-producer by construction).
    """

    def __init__(
        self,
        agg: ShardedAggregator,
        staging_buffers: int = 3,
        dispatch_ahead: int = 2,
        max_batch: int = 64,
        shard_parallel: bool | None = None,
        shard_threads: int = 0,
        packed: bool | None = None,
        tenant: str = "default",
        pool=None,
        scheduler=None,
    ):
        if staging_buffers < 2:
            raise ValueError("staging_buffers must be >= 2 (no overlap below that)")
        if dispatch_ahead < 1:
            raise ValueError("dispatch_ahead must be >= 1")
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.agg = agg
        self.staging_buffers = staging_buffers
        self.dispatch_ahead = dispatch_ahead
        self.max_batch = min(max_batch, MAX_LAZY_BATCH)
        # shard-parallel: one fold worker per mesh device, on by default
        # whenever the mesh actually has more than one (None = auto);
        # shard_threads pins the per-shard native thread budget (0 = split
        # the process budget across shards / XAYNET_NATIVE_SHARD_THREADS)
        n_dev = agg.mesh.devices.size
        self._sharded = n_dev > 1 and (shard_parallel is None or shard_parallel)
        self._n_shards = n_dev if self._sharded else 1
        self._shard_threads = shard_threads
        # packed staging (on by default wherever it shrinks anything): the
        # planar submit paths stage byte-planar uint8[K, bpn, width] planes
        # — bpn/(4L) of the unpacked ring/transfer bytes — and the fold
        # reads the packed planes directly (native) or unpacks in-graph
        # (device). The fold math is the exact same modular sum over the
        # exact same (validated, < order) elements, so the aggregate is
        # byte-identical to unpacked staging.
        self._packed = (
            agg.packed_staging_usable() if packed is None
            else bool(packed) and agg.packed_staging_usable()
        )
        # multi-tenant seam (docs/DESIGN.md §19): the tenant id labels this
        # pipeline's page leases, scheduler slots, spans and flight dumps;
        # the shared pool backs the staging rings and shard-plan buffers;
        # the scheduler interleaves this tenant's fold batches with other
        # tenants' on the one mesh (fairness + global in-flight bound)
        self.tenant = tenant
        self._pool = pool if pool is not None else get_pool()
        self._sched = scheduler if scheduler is not None else get_scheduler()
        self._sched_owner = self._sched.new_owner()
        # abandoned pipelines give their slots back at collection time
        weakref.finalize(self, self._sched.release_owner, self._sched_owner)
        self._plan = None  # shards.ShardPlan while accs live  # guarded-by: _lock
        self._shard_queues: list[queue_mod.Queue] | None = None
        self._shard_workers: list[threading.Thread | None] = []
        self._shard_rings: dict[int, _StagingRing] = {}  # guarded-by: _lock
        self._shard_stage_seconds = [0.0] * self._n_shards  # guarded-by: _lock
        self._shard_fold_seconds = [0.0] * self._n_shards  # guarded-by: _lock
        self._queue: queue_mod.Queue = queue_mod.Queue(maxsize=dispatch_ahead)
        self._rings: dict[str, _StagingRing] = {}  # lazy: planar / wire  # guarded-by: _lock
        self._pending: list[StreamTicket] = []  # awaiting ok sync  # guarded-by: _lock
        self._in_flight_models = 0  # submitted, not yet folded  # guarded-by: _lock
        self._error: BaseException | None = None  # guarded-by: _lock
        self._poison_seq: int | None = None  # poisoning batch index  # guarded-by: _lock
        self._flight_dumped = False  # one flight dump per pipeline  # guarded-by: _lock
        self._degraded = False  # sync path for the rest of the round  # guarded-by: _lock
        self._batch_seq = 0  # submit-order index: producer-thread confined
        self._worker: threading.Thread | None = None
        self._closed = False
        self._lock = threading.Lock()  # worker-shared counters/pending
        # a fresh pipeline is never degraded — reset the gauge here, not
        # only in close(): a degraded pipeline abandoned on phase failure
        # must not leave the gauge stuck at 1 for later healthy rounds
        DEGRADED.set(0)
        # overlap accounting, reset per drain window
        self._stage_seconds = 0.0
        self._fold_seconds = 0.0
        self._window_start: float | None = None

    # -- lifecycle ---------------------------------------------------------

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=_worker_main,
                args=(weakref.ref(self), self._queue),
                name="xn-stream-fold",
                daemon=True,
            )
            self._worker.start()
            # wake the worker if this pipeline is dropped without close()
            weakref.finalize(self, self._queue.put, _SHUTDOWN)

    def close(self) -> None:
        """Drain, then stop the fold worker. Idempotent. A poisoned
        pipeline (worker failure) still shuts down — the error has already
        surfaced (or will) through drain()/submit, and close() is the
        cleanup path."""
        if self._closed:
            return
        try:
            self.drain()
        except StreamingError:
            logger.warning("closing poisoned streaming pipeline")
        self._closed = True
        if self._degraded:  # lint: guarded-ok: post-drain, workers joined below
            DEGRADED.set(0)
        if self._worker is not None and self._worker.is_alive():
            self._queue.put(_SHUTDOWN)
            self._worker.join(timeout=60.0)
        if self._shard_queues is not None:
            for q in self._shard_queues:
                q.put(_SHUTDOWN)
            for w in self._shard_workers:
                if w is not None and w.is_alive():
                    w.join(timeout=60.0)
        if self._plan is not None:  # lint: guarded-ok: post-drain, workers joined above
            # shut the plan's fold pool; the per-shard buffers stay ADOPTED
            # by the aggregator (reduce-scatter) so finalize/unmask/snapshot
            # after close still read the accumulator — on a poisoned
            # pipeline they surface the error through drain() first
            self._plan.close()  # lint: guarded-ok: post-drain, workers joined above
            self._plan = None  # lint: guarded-ok: post-drain, workers joined above
        # staging pages go back to the pool (nothing is in flight past the
        # drain/joins above); the shard plan's accumulator pages stay
        # leased — unmask still reads them — and release through
        # StagedAggregator.release_pool / the round-boundary reclaim
        with self._lock:
            rings = list(self._rings.values()) + list(self._shard_rings.values())
            self._rings.clear()
            self._shard_rings.clear()
        for ring in rings:
            ring.close()
        self._sched.release_owner(self._sched_owner)

    # -- producer side -----------------------------------------------------

    @property
    def in_flight_models(self) -> int:
        """Submitted-but-uncredited update count (an upper bound for wire
        batches until their acceptance vector syncs at drain)."""
        with self._lock:
            return self._in_flight_models

    def counted_models(self) -> int:
        """``in_flight + agg.nb_models`` read atomically with the worker's
        per-batch handoff (credit nb_models / drop in-flight under the same
        lock), so a caller's capacity check (TooManyModels) never sees a
        batch double-counted mid-fold or dropped between fold and drain."""
        with self._lock:
            return self._in_flight_models + self.agg.nb_models

    @property
    def degraded(self) -> bool:
        """True once a fold failure switched the pipeline to the
        synchronous fold path (the round still completes)."""
        with self._lock:
            return self._degraded

    def _ring(self, kind: str) -> _StagingRing:
        with self._lock:
            ring = self._rings.get(kind)
            if ring is None:
                agg = self.agg
                if kind == "planar":
                    shape = (self.max_batch, agg.n_limbs, agg.padded_length)
                    dtype = np.uint32
                elif kind == "packed":
                    # byte-planar packed planes: bpn/(4L) of the planar ring
                    shape = (self.max_batch, agg.packed_width, agg.padded_length)
                    dtype = np.uint8
                else:  # raw wire bytes
                    shape = (self.max_batch, agg.padded_length * agg.config.bytes_per_number)
                    dtype = np.uint8
                # first-call buffer allocation happens under the lock: once
                # per kind, before any overlap exists to lose
                ring = self._rings[kind] = _StagingRing(
                    self.staging_buffers, shape, dtype,
                    pool=self._pool, tenant=self.tenant,
                )
            return ring

    # -- tenant fold-batch slots (docs/DESIGN.md §19) ----------------------
    #
    # Every batch holds ONE scheduler slot from dispatch until its fold
    # settles (worker completion / last-shard commit / the degraded-path
    # finally). The slot is the cross-tenant interleave point: the
    # scheduler grants it fairly across tenants and bounds the mesh-wide
    # in-flight total, which is the multi-tenant backpressure.

    def _slot_acquire(self) -> None:
        self._sched.acquire(self.tenant, self._sched_owner)

    def _slot_release(self) -> None:
        self._sched.release(self._sched_owner)

    def _flight_poison(self, cause: BaseException, seq: int | None) -> None:
        """ONE forensic dump per pipeline (idempotent under the lock): the
        span ring holds the poisoning batch's stage/fold (and per-shard)
        spans. Worker paths call this AFTER the failing batch's spans have
        closed — a dump taken inside the open span would miss exactly the
        spans it exists to capture."""
        with self._lock:
            if self._flight_dumped:
                return
            self._flight_dumped = True
        flight_dump(
            "pipeline-poison",
            f"batch {seq}: {type(cause).__name__}: {cause}",
            batch=seq,
            tenant=self.tenant,
        )

    def _poison_error(self) -> StreamingError:
        """The sticky error, always naming the poisoning batch and cause."""
        with self._lock:
            cause = self._error
            seq = self._poison_seq
        where = f"batch {seq}" if seq is not None else "deferred sync"
        return StreamingError(
            f"streaming pipeline poisoned at {where}: "
            f"{type(cause).__name__}: {cause}"
        )

    def _poisoned(self) -> BaseException | None:
        """Locked read of the sticky error (producer-side checks)."""
        with self._lock:
            return self._error

    def _check(self, k: int) -> None:
        if self._closed:
            raise StreamingError("pipeline is closed")
        err = self._poisoned()
        if err is not None:
            raise self._poison_error() from err
        if k > self.max_batch:
            raise ValueError(f"batch of {k} exceeds max_batch={self.max_batch}")
        if self._window_start is None:
            self._window_start = time.monotonic()

    def _dispatch(self, item: tuple) -> None:
        """Queue to the fold worker — or, once degraded, fold synchronously
        on the caller's thread (same math, no overlap)."""
        buf, payload, kind, k, ticket, seq = item
        self._slot_acquire()  # released when the fold settles (_process)
        with self._lock:
            self._in_flight_models += k
            degraded = self._degraded
        BATCHES_TOTAL.labels(stage="staged").inc()
        if not degraded:
            self._ensure_worker()
            INFLIGHT_FOLDS.inc()
            self._queue.put(item)
            return
        t0 = time.monotonic()
        try:
            # serialize with the worker: batches queued BEFORE degradation
            # (including the retry that flipped the flag) must finish before
            # a caller-thread fold touches agg.acc — two unsynchronized
            # mutators would lose updates
            self._queue.join()
            err = self._poisoned()
            if err is not None:
                raise self._poison_error() from err
            self._fold_payload(payload, kind, k, ticket, defer_ok=False)
        except StreamingError:
            # already-poisoned pipeline: this batch just leaves flight
            with self._lock:
                self._in_flight_models -= k
            BATCHES_TOTAL.labels(stage="failed").inc()
            raise
        except BaseException as e:
            unsafe = isinstance(e, _UnsafeFoldError)
            cause = (e.__cause__ or e) if unsafe else e
            with self._lock:
                first = self._error is None
                self._error = cause
                self._poison_seq = seq
                if not (unsafe and e.settled):
                    self._in_flight_models -= k
            if first:
                self._flight_poison(cause, seq)
            BATCHES_TOTAL.labels(stage="failed").inc()
            raise self._poison_error() from cause
        finally:
            self._slot_release()
            self._ring(kind).release(buf)
            with self._lock:
                self._fold_seconds += time.monotonic() - t0
        BATCHES_TOTAL.labels(stage="folded").inc()

    def submit_batch(self, stack: np.ndarray) -> StreamTicket:
        """Stage + stream-fold wire-layout ``uint32[K, model_len, L]``
        updates (the pre-validated path: all members count immediately)."""
        stack = np.asarray(stack, dtype=np.uint32)  # host input, no device sync  # lint: sync-ok
        if stack.ndim != 3 or stack.shape[2] != self.agg.n_limbs:
            raise ValueError("expected uint32[K, model_len, L]")
        if stack.shape[1] != self.agg.model_length:
            raise ValueError("model length mismatch")
        k = stack.shape[0]
        self._check(k)
        if self._sharded:
            return self._submit_sharded_planar_stack(stack, k)
        from ..ops import limbs as host_limbs

        kind = "packed" if self._packed else "planar"
        t0 = time.monotonic()
        buf = self._ring(kind).acquire()
        view = buf[:k]
        if self._packed:
            # pack straight into the byte-planar ring buffer: one strided
            # transpose of the first bpn wire bytes per element — the same
            # copy class as the planar transpose below, writing bpn/(4L)
            # of the bytes
            host_limbs.pack_wire(stack, self.agg.packed_width, out=view[:, :, : self.agg.model_length])
            if self.agg.padded_length != self.agg.model_length:
                view[:, :, self.agg.model_length :] = 0
        else:
            # transpose+pad straight into the ring buffer (numpy strided
            # copy, no wire_to_planar intermediate): per-batch host
            # allocation in the steady state is zero
            view[:, :, : self.agg.model_length] = stack.transpose(0, 2, 1)
            if self.agg.padded_length != self.agg.model_length:
                view[:, :, self.agg.model_length :] = 0
        BYTES_STAGED.labels(layout="packed" if self._packed else "unpacked").inc(view.nbytes)
        ticket = StreamTicket(k)
        self._stage_seconds += time.monotonic() - t0
        self._batch_seq += 1
        trace.get_tracer().record_span(
            SPAN_STAGE, start=t0, duration=time.monotonic() - t0,
            batch=self._batch_seq, kind=kind, k=k,
        )
        self._dispatch((buf, view, kind, k, ticket, self._batch_seq))
        return ticket

    def fold_planar_rows_now(self, rows: list) -> None:
        """Fold already device-resident, validity-checked planar
        ``[L, padded_len]`` updates on the CALLER's thread (the wire-ingest
        server path: validated planars cached by ``validate_wire_update(s)``).

        Deliberately NOT queued: these rows already occupy device memory,
        so parking them behind ``dispatch_ahead`` would pin up to
        ``dispatch_ahead + 1`` full batches in HBM (~13 GB each at
        25M/batch 64) — and XLA's own asynchronous dispatch already
        overlaps device-side folds without our queue. Waits out queued
        work first (``agg.acc`` has exactly one mutator at a time), then
        stacks + folds in chunks, dropping consumed references, so peak
        device memory stays at the staged rows + one chunk-sized copy —
        the same bound as the pre-streaming flush."""
        if not rows:
            return
        if self._sharded:
            return self._fold_planar_rows_now_sharded(rows)
        self._queue.join()
        err = self._poisoned()
        if err is not None:
            raise self._poison_error() from err
        if self._closed:
            raise StreamingError("pipeline is closed")
        import jax
        import jax.numpy as jnp

        agg = self.agg
        rows = list(rows)
        while rows:
            piece, rows = rows[:8], rows[8:]
            staged = jax.device_put(jnp.stack(piece), agg._batch_sharding)
            n_piece = len(piece)
            del piece
            # caller-thread folds hold a scheduler slot per chunk too, so
            # the device-resident fast path cannot starve other tenants
            self._slot_acquire()
            try:
                agg.acc = agg._fold(agg.acc, staged)
            finally:
                self._slot_release()
            with self._lock:
                agg.nb_models += n_piece

    def fold_packed_rows_now(self, rows: list) -> None:
        """Fold already device-resident, validity-checked PACKED byte-planar
        ``uint8[bpn, padded_len]`` updates on the CALLER's thread — the
        wire-v2 ingest path (``validate_planar_update(s)`` keeps accepted
        rows in their staged packed layout, ``bpn`` bytes/element instead
        of the ``4L`` a resident uint32 planar would pin). Same
        no-queueing rationale and accounting as
        :meth:`fold_planar_rows_now`; the fold itself is the fused packed
        kernel (``agg._fold_packed``), so the uint32 expansion only ever
        exists transiently inside the jit. In shard-parallel mode the rows
        are unpacked on device (still no host materialization) and folded
        through the per-shard planar fan-out."""
        if not rows:
            return
        if self._sharded:
            from ..ops.limbs_jax import packed_planar_to_limbs_jit

            n_limbs = self.agg.n_limbs
            return self._fold_planar_rows_now_sharded(
                [packed_planar_to_limbs_jit(r, n_limbs) for r in rows]
            )
        self._queue.join()
        err = self._poisoned()
        if err is not None:
            raise self._poison_error() from err
        if self._closed:
            raise StreamingError("pipeline is closed")
        import jax
        import jax.numpy as jnp

        agg = self.agg
        rows = list(rows)
        while rows:
            piece, rows = rows[:8], rows[8:]
            staged = jax.device_put(jnp.stack(piece), agg._batch_packed_sharding)
            n_piece = len(piece)
            del piece
            # the packed fold never drives kernel auto-calibration (see
            # agg._fold_packed) — resolve on the cheap path first
            agg._resolve_kernel_cheap(n_piece)
            self._slot_acquire()
            try:
                agg.acc = agg._fold_packed(agg.acc, staged)
            finally:
                self._slot_release()
            with self._lock:
                agg.nb_models += n_piece

    def fold_planar_stack_now(self, stacked) -> None:
        """Fold an already device-resident planar ``[K, L, padded_len]``
        BATCH on the CALLER's thread — the fused-mask-pipeline shape
        (``ops.masking_jax``): a whole seed group's mask planes come out of
        one jitted derive as a single stacked array, so re-slicing it into
        rows only to re-stack them would buy two copies. Same rationale and
        accounting as :meth:`fold_planar_rows_now` (device-resident batches
        are never queued; ``agg.acc`` has one mutator at a time); in
        shard-parallel mode each shard folds its addressable slice."""
        if stacked.shape[0] == 0:
            return
        k = int(stacked.shape[0])
        import jax

        agg = self.agg
        if self._sharded:
            self._join_shard_queues()
            err = self._poisoned()
            if err is not None:
                raise self._poison_error() from err
            if self._closed:
                raise StreamingError("pipeline is closed")
            plan = self._ensure_plan(k, lambda: stacked)
            # pin the mesh layout: the derive emits a single-device array,
            # and the per-shard fan-out reads addressable shards
            stacked = jax.device_put(stacked, agg._batch_sharding)
            self._fold_pinned_stack(plan, stacked, k)
            return
        self._queue.join()
        err = self._poisoned()
        if err is not None:
            raise self._poison_error() from err
        if self._closed:
            raise StreamingError("pipeline is closed")
        agg._resolve_kernel_cheap(k)
        self._slot_acquire()
        try:
            new_acc = agg._fold(agg.acc, stacked)
        finally:
            self._slot_release()
        with self._lock:
            agg.acc = new_acc
            agg.nb_models += k

    def submit_host_planar_rows(self, rows: list) -> StreamTicket:
        """Stream-fold host planar ``[L, padded_len]`` rows (numpy), copied
        into a ring buffer here so the caller can recycle its arrays."""
        k = len(rows)
        if k == 0:
            raise ValueError("empty planar batch")
        self._check(k)
        if self._sharded:
            return self._submit_sharded_planar_rows(rows, k)
        from ..ops import limbs as host_limbs

        kind = "packed" if self._packed else "planar"
        t0 = time.monotonic()
        buf = self._ring(kind).acquire()
        view = buf[:k]
        for i, row in enumerate(rows):
            if self._packed:
                host_limbs.pack_planar(row, self.agg.packed_width, out=view[i])
            else:
                np.copyto(view[i], row)
        BYTES_STAGED.labels(layout="packed" if self._packed else "unpacked").inc(view.nbytes)
        ticket = StreamTicket(k)
        self._stage_seconds += time.monotonic() - t0
        self._batch_seq += 1
        trace.get_tracer().record_span(
            SPAN_STAGE, start=t0, duration=time.monotonic() - t0,
            batch=self._batch_seq, kind=kind, k=k,
        )
        self._dispatch((buf, view, kind, k, ticket, self._batch_seq))
        return ticket

    def submit_wire_batch(self, raw: np.ndarray) -> StreamTicket:
        """Stage + stream-fold RAW wire element blocks
        ``uint8[K, model_len * bpn]``. Acceptance is DEFERRED: the per-member
        ``bool[K]`` lands on the ticket at the next ``drain()`` (the fold
        itself excludes invalid members either way)."""
        agg = self.agg
        bpn = agg.config.bytes_per_number
        raw = np.asarray(raw)  # host input, no device sync  # lint: sync-ok
        if raw.dtype != np.uint8 or raw.ndim != 2 or raw.shape[1] != agg.model_length * bpn:
            raise ValueError("expected uint8[K, model_len * bytes_per_number]")
        k = raw.shape[0]
        self._check(k)
        t0 = time.monotonic()
        ring = self._ring("wire")
        buf = ring.acquire()
        view = buf[:k]
        view[:, : raw.shape[1]] = raw
        if agg.padded_length != agg.model_length:
            view[:, raw.shape[1] :] = 0  # zero bytes decode to zero elements
        BYTES_STAGED.labels(layout="wire").inc(view.nbytes)
        ticket = StreamTicket(k)
        self._stage_seconds += time.monotonic() - t0
        trace.get_tracer().record_span(
            SPAN_STAGE, start=t0, duration=time.monotonic() - t0,
            batch=self._batch_seq + 1, kind="wire", k=k,
        )
        if self._sharded:
            return self._dispatch_sharded_wire(ring, buf, view, k, ticket)
        self._batch_seq += 1
        self._dispatch((buf, view, "wire", k, ticket, self._batch_seq))
        return ticket

    # -- fold worker -------------------------------------------------------

    def _credit(self, staged, k: int, packed: bool = False) -> None:
        """Fold a planar (or packed byte-planar) batch and hand its count
        over atomically: the nb_models credit and the in-flight drop happen
        under one lock, so ``counted_models()`` never observes the batch
        twice (double count → spurious TooManyModels near the cap) or zero
        times."""
        agg = self.agg
        fold = agg._fold_packed if packed else agg._fold
        new_acc = fold(agg.acc, staged)
        with self._lock:
            agg.acc = new_acc
            agg.nb_models += k
            self._in_flight_models -= k

    def _fold_payload(self, payload, kind: str, k: int, ticket, defer_ok: bool) -> None:
        """Fold one staged batch. ``defer_ok=True`` (worker path) leaves a
        wire batch's acceptance vector in flight for drain's single sync;
        ``defer_ok=False`` (degraded sync path) resolves it immediately.

        Failure classes matter here: the accumulator is reassigned only
        when a fold call RETURNS, so an exception raised before/inside the
        fold leaves ``agg.acc`` consistent (the degrade path may retry the
        batch). Failures after that point — the ring-buffer transfer wait
        and the acceptance fetch — are wrapped in ``_UnsafeFoldError``:
        retrying them would double-fold the batch."""
        import jax

        agg = self.agg
        if kind == "wire":
            staged = jax.device_put(payload, agg._batch_bytes_sharding)
            ok = agg.dispatch_staged_bytes(staged)
            # -- acc now references this batch: no retry beyond this line --
            if defer_ok:
                ticket._ok = ok
                with self._lock:
                    self._pending.append(ticket)
                try:
                    # the transfer out of the ring buffer must complete
                    # before reuse; the fold itself stays in flight behind it
                    jax.block_until_ready(staged)  # lint: sync-ok
                except BaseException as e:
                    with self._lock:
                        if ticket in self._pending:
                            self._pending.remove(ticket)
                    ticket._ok = None
                    raise _UnsafeFoldError() from e
                return
            try:
                ok_host = np.asarray(ok)  # acceptance sync (and fold barrier)  # lint: sync-ok
            except BaseException as e:
                raise _UnsafeFoldError() from e
            ticket.accepted = ok_host
            with self._lock:
                agg.nb_models += int(ok_host.sum())
                self._in_flight_models -= k
            return
        packed = kind == "packed"
        agg._resolve_kernel_cheap(k)
        if packed and agg.kernel_used is None:
            # the auto race calibrates on a PLANAR staged batch (both
            # candidate folds take that shape): unpack this batch once on
            # the host for the one-time timing run, then fold the packed
            # original through the winner
            from ..ops import limbs as host_limbs

            planar = host_limbs.unpack_planar(
                np.asarray(payload), agg.n_limbs  # host ring view  # lint: sync-ok
            )
            agg._resolve_kernel(jax.device_put(planar, agg._batch_sharding))
        if agg.kernel_used == "native-u64":
            # host fold reads the ring buffer directly (synchronous)
            # — no device staging at all (packed: the byte planes fold
            # in place through the native packed kernel)
            self._credit(payload, k, packed=packed)
        else:
            staged = jax.device_put(
                payload, agg._batch_packed_sharding if packed else agg._batch_sharding
            )
            self._credit(staged, k, packed=packed)
            try:
                jax.block_until_ready(staged)  # host buffer free to reuse  # lint: sync-ok
            except BaseException as e:
                # _credit already handed the count off: settled
                raise _UnsafeFoldError(settled=True) from e
        ticket.accepted = np.ones(k, dtype=bool)

    def _degrade_and_retry(self, payload, kind: str, k: int, ticket, seq: int,
                           first: BaseException) -> str:
        """First fold failure with a consistent accumulator: switch the
        pipeline to the synchronous path and retry the batch once. Returns
        the outcome label; a second failure poisons permanently."""
        logger.warning(
            "streaming fold failed at batch %d (%s: %s); retrying on the "
            "synchronous path and degrading the pipeline",
            seq,
            type(first).__name__,
            first,
        )
        with self._lock:
            self._degraded = True
        DEGRADED.set(1)
        DEGRADATIONS.inc()
        try:
            self._fold_payload(payload, kind, k, ticket, defer_ok=False)
            return "folded-degraded"
        except BaseException as second:
            # the batch is lost: the accumulator no longer matches any
            # consistent update set — poison permanently, with the batch
            # index and root cause on every later error (the caller fires
            # the flight dump once its span has closed)
            unsafe = isinstance(second, _UnsafeFoldError)
            cause = (second.__cause__ or second) if unsafe else second
            cause.__context__ = first
            with self._lock:
                self._error = cause
                self._poison_seq = seq
                if not (unsafe and second.settled):
                    self._in_flight_models -= k
            logger.exception("streaming fold batch %d lost; pipeline poisoned", seq)
            return "failed"

    def _process(self, item: tuple) -> None:
        """Worker-side fold with the degradation ladder: streaming fold ->
        one synchronous retry (switching the pipeline to sync mode) ->
        sticky poison naming the batch and the original exception."""
        if isinstance(item[0], _UnmaskJob):  # eager unmask tail item
            return self._process_unmask(item)
        if isinstance(item[0], _BatchJob):  # shard-parallel item
            return self._process_shard(item)
        buf, payload, kind, k, ticket, seq = item
        agg_t0 = time.monotonic()
        outcome = "folded"
        with trace.get_tracer().span(SPAN_FOLD, batch=seq, kind=kind, k=k) as fold_span:
            try:
                try:
                    maybe_fail("streaming.fold")
                    self._fold_payload(payload, kind, k, ticket, defer_ok=True)
                except BaseException as first:
                    if isinstance(first, _UnsafeFoldError):
                        # acc may already reference the batch: retrying would
                        # double-fold it — poison straight away
                        cause = first.__cause__ or first
                        with self._lock:
                            self._error = cause
                            self._poison_seq = seq
                            if not first.settled:
                                self._in_flight_models -= k
                        outcome = "failed"
                        logger.exception(
                            "streaming fold batch %d failed post-dispatch; pipeline poisoned",
                            seq,
                        )
                    else:
                        outcome = self._degrade_and_retry(payload, kind, k, ticket, seq, first)
            finally:
                self._slot_release()
                if buf is not None:
                    self._ring(kind).release(buf)
                with self._lock:
                    self._fold_seconds += time.monotonic() - agg_t0
                INFLIGHT_FOLDS.dec()
                # a failed fold is NOT folded: dashboards comparing staged vs
                # folded must be able to see the loss
                BATCHES_TOTAL.labels(stage=outcome).inc()
                fold_span.set(outcome=outcome)
        if outcome == "failed":
            # the dump fires AFTER the batch's fold span closed, so the
            # ring it snapshots contains the poisoning batch's spans
            with self._lock:
                cause, pseq = self._error, self._poison_seq
            if cause is not None:
                self._flight_poison(cause, pseq)

    # -- drain -------------------------------------------------------------

    def drain(self) -> int:
        """Wait for every in-flight fold, then perform the ONE deferred
        acceptance sync: fetch all pending ``ok`` vectors, resolve their
        tickets, credit ``nb_models``. Returns the number of updates
        accepted from deferred wire batches in this window.

        In shard-parallel mode this is the CROSS-SHARD BARRIER: every
        shard queue drains, every shard's device folds complete, and the
        per-shard accumulators reassemble into the aggregator's global
        ``acc`` before anything reads it."""
        with trace.get_tracer().span(SPAN_DRAIN, sharded=self._sharded):
            return self._drain_inner()

    def _drain_inner(self) -> int:
        if self._sharded:
            return self._drain_sharded()
        self._queue.join()
        err = self._poisoned()
        if err is not None:
            # the pipeline is poisoned — PERMANENTLY: once the degraded
            # retry has also failed the accumulator no longer corresponds
            # to any consistent update set, so every later drain (finalize,
            # close) must keep failing rather than let a snapshot with
            # missing/uncounted updates escape as a valid round result.
            # The deferred state is discarded once (stale tickets must not
            # resolve and their counts must leave flight).
            with self._lock:
                stale, self._pending = self._pending, []
                self._in_flight_models -= sum(t.k for t in stale)
            for ticket in stale:
                ticket._ok = None
            raise self._poison_error() from err
        with self._lock:
            pending, self._pending = self._pending, []
        accepted = 0
        try:
            for ticket in pending:
                ok_host = np.asarray(ticket._ok)
                ticket._ok = None
                ticket.accepted = ok_host
                accepted += int(ok_host.sum())
            # a true completion barrier: the worker only blocks on staged
            # INPUTS (ring-buffer reuse), so with profiling off the last
            # folds may still be executing behind XLA's async dispatch —
            # and their errors surface here, not in the worker
            import jax

            jax.block_until_ready(self.agg.acc)
        except Exception as e:
            # an asynchronously-dispatched fold failed (e.g. device OOM):
            # the accumulator may already reference the failed computation,
            # so no consistent synchronous retry exists — poison exactly
            # like an exhausted worker retry (drop the deferred counts and
            # keep every later drain failing)
            with self._lock:
                fresh = self._error is None
                self._error = e
                self._in_flight_models -= sum(t.k for t in pending)
            for ticket in pending:
                ticket._ok = None
            if fresh:
                self._flight_poison(e, None)
            raise self._poison_error() from e
        if pending:
            # the ONE deferred credit: the accepted count lands and the
            # optimistic in-flight count drops in the same locked step, so
            # counted_models() never dips (folded-but-uncredited) nor
            # double-counts
            with self._lock:
                self.agg.nb_models += accepted
                self._in_flight_models -= sum(t.k for t in pending)
        self._publish_overlap()
        return accepted

    def _publish_overlap(self) -> None:
        if self._window_start is None:
            return
        wall = max(time.monotonic() - self._window_start, 1e-9)
        with self._lock:  # the drain barrier already quiesced the workers
            shorter = min(self._stage_seconds, self._fold_seconds)
            if shorter > 0:
                overlap = (self._stage_seconds + self._fold_seconds - wall) / shorter
                OVERLAP_RATIO.set(max(0.0, min(1.0, overlap)))
            if self._sharded:
                for d in range(self._n_shards):
                    s, f = self._shard_stage_seconds[d], self._shard_fold_seconds[d]
                    sh = min(s, f)
                    if sh > 0:
                        ov = (s + f - wall) / sh
                        SHARD_OVERLAP.labels(shard=str(d)).set(max(0.0, min(1.0, ov)))
                    self._shard_stage_seconds[d] = 0.0
                    self._shard_fold_seconds[d] = 0.0
            self._stage_seconds = 0.0
            self._fold_seconds = 0.0
        self._window_start = None

    # -- shard-parallel mode ----------------------------------------------
    #
    # One fold worker per mesh shard. The producer slices each padded
    # batch once on the host into per-shard staging rings; the batch
    # commits only when EVERY shard folded its slice (_BatchJob); drain()
    # is the cross-shard barrier that reassembles the per-shard donated
    # accumulators (shards.ShardPlan) into the aggregator's global acc.

    def _ensure_plan(self, k: int, calib_staged):
        """Resolve the fold kernel (racing XLA against the per-shard native
        fold on the first real batch, exactly like the sequential path) and
        build the shard plan. ``calib_staged`` lazily produces a full
        staged planar ``[K, L, padded]`` (host or device) — only invoked
        when an auto verdict is not already memoized for this shape."""
        agg = self.agg
        if agg.kernel_used is None:
            agg._resolve_kernel_cheap(k)
            if agg.kernel_used is None:
                import jax

                staged = calib_staged()
                if not isinstance(staged, jax.Array):
                    staged = jax.device_put(staged, agg._batch_sharding)
                agg._resolve_kernel(staged)
        with self._lock:
            plan = self._plan
        if plan is not None and agg._live_plan is not plan:
            # an explicit accumulator write (restore/reset) superseded the
            # adopted plan: the per-shard buffers are stale — shut its
            # fold pool (only this producer folds into it, so nothing is
            # in flight), give its pages back, and rebuild
            plan.close()
            plan.release_pages()
            plan = None
        if plan is None:
            from .shards import ShardPlan

            # built outside the lock (device work); the single producer is
            # the only creator, the lock just publishes the reference.
            # The plan is ADOPTED by the aggregator (reduce-scatter): it
            # persists across drain windows as the authoritative
            # accumulator, so the per-drain reassemble+decompose round
            # trip is gone — the only gathers left are explicit acc reads
            plan = ShardPlan(
                agg,
                shard_threads=self._shard_threads,
                pool=self._pool,
                tenant=self.tenant,
            )
            agg.adopt_plan(plan)
            with self._lock:
                self._plan = plan
        return plan

    def _shard_ring(self, d: int) -> _StagingRing:
        with self._lock:
            ring = self._shard_rings.get(d)
            if ring is None:
                agg = self.agg
                width = agg.padded_length // self._n_shards
                if self._packed:
                    shape: tuple = (self.max_batch, agg.packed_width, width)
                    dtype = np.uint8
                else:
                    shape = (self.max_batch, agg.n_limbs, width)
                    dtype = np.uint32
                ring = self._shard_rings[d] = _StagingRing(
                    self.staging_buffers,
                    shape,
                    dtype,
                    gauge=SHARD_STAGING_DEPTH.labels(shard=str(d)),
                    pool=self._pool,
                    tenant=self.tenant,
                )
            return ring

    def _ensure_shard_workers(self) -> None:
        if self._shard_queues is None:
            self._shard_queues = [
                queue_mod.Queue(maxsize=self.dispatch_ahead)
                for _ in range(self._n_shards)
            ]
            self._shard_workers = [None] * self._n_shards
            for q in self._shard_queues:
                # wake the worker if this pipeline is dropped without close()
                weakref.finalize(self, q.put, _SHUTDOWN)
        for i, q in enumerate(self._shard_queues):
            w = self._shard_workers[i]
            if w is None or not w.is_alive():
                w = threading.Thread(
                    target=_worker_main,
                    args=(weakref.ref(self), q),
                    name=f"xn-stream-fold-{i}",
                    daemon=True,
                )
                self._shard_workers[i] = w
                w.start()

    def _join_shard_queues(self) -> None:
        for q in self._shard_queues or []:
            q.join()

    def _poison(self, cause: BaseException, seq: int) -> None:
        with self._lock:
            if self._error is None:
                self._error = cause
                self._poison_seq = seq

    def _submit_sharded_planar_stack(self, stack: np.ndarray, k: int) -> StreamTicket:
        """Slice the wire batch ONCE on the host into the per-shard planar
        rings (each shard's slice transposed straight into its ring buffer
        — no full-planar intermediate) and dispatch one item per shard."""
        ticket = StreamTicket(k)
        agg = self.agg
        model_len = agg.model_length

        def calib():
            full = np.zeros((k, agg.n_limbs, agg.padded_length), dtype=np.uint32)
            full[:, :, :model_len] = stack.transpose(0, 2, 1)
            return full

        plan = self._ensure_plan(k, calib)
        from ..ops import limbs as host_limbs

        kind = "packed" if self._packed else "planar"
        self._batch_seq += 1
        job = _BatchJob(kind, k, ticket, self._batch_seq, self._n_shards)
        items = []
        for d, (lo, hi) in enumerate(plan.slices):
            t0 = time.monotonic()
            ring = self._shard_ring(d)
            buf = ring.acquire()
            view = buf[:k]
            real_hi = min(hi, model_len)
            if lo < real_hi:
                if self._packed:
                    # pack this shard's wire slice straight into its
                    # byte-planar ring buffer (the native plane-pack
                    # kernel: bpn/(4L) of the bytes the planar transpose
                    # would write, at memcpy speed)
                    host_limbs.pack_wire_slice(
                        stack, lo, real_hi, self.agg.packed_width, view
                    )
                else:
                    view[:, :, : real_hi - lo] = stack[:, lo:real_hi, :].transpose(0, 2, 1)
            if real_hi < hi:
                view[:, :, max(0, real_hi - lo):] = 0  # padding columns
            BYTES_STAGED.labels(
                layout="packed" if self._packed else "unpacked"
            ).inc(view.nbytes)
            dt = time.monotonic() - t0
            with self._lock:
                self._stage_seconds += dt
                self._shard_stage_seconds[d] += dt
            trace.get_tracer().record_span(
                SPAN_STAGE, start=t0, duration=dt, batch=job.seq, shard=d, k=k
            )
            items.append((job, d, view, ring, buf))
        self._dispatch_sharded(job, items)
        return ticket

    def _submit_sharded_planar_rows(self, rows: list, k: int) -> StreamTicket:
        """Per-shard staging of host planar ``[L, padded]`` rows (sliced
        once per shard, copied into that shard's ring buffer)."""
        ticket = StreamTicket(k)
        plan = self._ensure_plan(k, lambda: np.stack([np.asarray(r) for r in rows]))  # host rows  # lint: sync-ok
        from ..ops import limbs as host_limbs

        kind = "packed" if self._packed else "planar"
        self._batch_seq += 1
        job = _BatchJob(kind, k, ticket, self._batch_seq, self._n_shards)
        items = []
        for d, (lo, hi) in enumerate(plan.slices):
            t0 = time.monotonic()
            ring = self._shard_ring(d)
            buf = ring.acquire()
            view = buf[:k]
            for i, row in enumerate(rows):
                if self._packed:
                    host_limbs.pack_planar_slice(
                        np.asarray(row), lo, hi, self.agg.packed_width, view[i]  # host rows  # lint: sync-ok
                    )
                else:
                    np.copyto(view[i], row[:, lo:hi])
            BYTES_STAGED.labels(
                layout="packed" if self._packed else "unpacked"
            ).inc(view.nbytes)
            dt = time.monotonic() - t0
            with self._lock:
                self._stage_seconds += dt
                self._shard_stage_seconds[d] += dt
            trace.get_tracer().record_span(
                SPAN_STAGE, start=t0, duration=dt, batch=job.seq, shard=d, k=k
            )
            items.append((job, d, view, ring, buf))
        self._dispatch_sharded(job, items)
        return ticket

    def _dispatch_sharded(self, job: _BatchJob, items: list) -> None:
        """Queue one item per shard worker — or, once degraded, fold every
        shard on the caller's thread after a full queue barrier (same math,
        no overlap; the batch still commits atomically)."""
        self._slot_acquire()  # one slot per BATCH; the last shard releases
        with self._lock:
            self._in_flight_models += job.k
            degraded = self._degraded
        BATCHES_TOTAL.labels(stage="staged").inc()
        if not degraded:
            self._ensure_shard_workers()
            INFLIGHT_FOLDS.inc()
            for item, q in zip(items, self._shard_queues):
                SHARD_INFLIGHT.labels(shard=str(item[1])).inc()
                q.put(item)
            return
        t0 = time.monotonic()
        released = [False] * len(items)
        try:
            # serialize with the shard workers: batches queued BEFORE the
            # degradation must land before caller-thread folds touch the
            # per-shard accumulators
            self._join_shard_queues()
            err = self._poisoned()
            if err is not None:
                raise self._poison_error() from err
            for i, (jb, d, payload, ring, buf) in enumerate(items):
                try:
                    self._fold_shard_item(jb, d, payload)
                finally:
                    if ring is not None:
                        ring.release(buf)
                    released[i] = True
            with self._lock:
                self.agg.nb_models += job.k
                self._in_flight_models -= job.k
        except StreamingError:
            with self._lock:
                self._in_flight_models -= job.k
            BATCHES_TOTAL.labels(stage="failed").inc()
            raise
        except BaseException as e:
            unsafe = isinstance(e, _UnsafeFoldError)
            cause = (e.__cause__ or e) if unsafe else e
            self._poison(cause, job.seq)
            with self._lock:
                self._in_flight_models -= job.k
            BATCHES_TOTAL.labels(stage="failed").inc()
            raise self._poison_error() from cause
        finally:
            self._slot_release()
            for i, (_jb, _d, _p, ring, buf) in enumerate(items):
                if not released[i] and ring is not None:
                    ring.release(buf)
            with self._lock:
                self._fold_seconds += time.monotonic() - t0
        BATCHES_TOTAL.labels(stage="folded").inc()

    def _dispatch_sharded_wire(
        self, ring: _StagingRing, buf, view, k: int, ticket: StreamTicket
    ) -> StreamTicket:
        """Wire batches keep ONE mesh unpack program (the psum-consistent
        per-update validity mask of the sequential path — an update invalid
        on ANY shard is excluded on EVERY shard) and fan only the fold out
        to the per-shard workers: each worker folds its addressable shard
        of the already-masked planar. Acceptance stays deferred: the ``ok``
        vector rides in flight until drain's single sync."""
        import jax

        agg = self.agg
        self._batch_seq += 1
        seq = self._batch_seq
        self._slot_acquire()  # covers the mesh unpack below; the last
        # shard's commit (or a failure here) releases it
        try:
            staged = jax.device_put(view, agg._batch_bytes_sharding)
            planar_mesh, ok = profiling.timed_kernel(
                "wire_unpack",
                staged.shape[0] * agg.padded_length,
                lambda: agg._make_unpack_fn()(staged),
            )
            plan = self._ensure_plan(k, lambda: planar_mesh)
        except BaseException as e:
            self._slot_release()
            ring.release(buf)
            self._poison(e, seq)
            BATCHES_TOTAL.labels(stage="failed").inc()
            raise self._poison_error() from e
        by_start = {
            s.index[-1].start or 0: s.data for s in planar_mesh.addressable_shards
        }
        job = _BatchJob("wire", k, ticket, seq, self._n_shards)
        job.staged = staged
        job.global_release = (ring, buf)
        with self._lock:
            self._in_flight_models += k
            degraded = self._degraded
        BATCHES_TOTAL.labels(stage="staged").inc()
        if degraded:
            released = False
            try:
                self._join_shard_queues()
                err = self._poisoned()
                if err is not None:
                    raise self._poison_error() from err
                ok_host = np.asarray(ok)  # acceptance sync (degraded path)  # lint: sync-ok
                ticket.accepted = ok_host
                for d, (lo, _hi) in enumerate(plan.slices):
                    self._fold_shard_item(job, d, by_start[lo])
                jax.block_until_ready(staged)  # lint: sync-ok
                ring.release(buf)
                released = True
                with self._lock:
                    self.agg.nb_models += int(ok_host.sum())
                    self._in_flight_models -= k
            except StreamingError:
                with self._lock:
                    self._in_flight_models -= k
                BATCHES_TOTAL.labels(stage="failed").inc()
                raise
            except BaseException as e:
                unsafe = isinstance(e, _UnsafeFoldError)
                cause = (e.__cause__ or e) if unsafe else e
                self._poison(cause, seq)
                with self._lock:
                    self._in_flight_models -= k
                BATCHES_TOTAL.labels(stage="failed").inc()
                raise self._poison_error() from cause
            finally:
                self._slot_release()
                if not released:
                    ring.release(buf)
            BATCHES_TOTAL.labels(stage="folded").inc()
            return ticket
        ticket._ok = ok
        with self._lock:
            self._pending.append(ticket)
        self._ensure_shard_workers()
        INFLIGHT_FOLDS.inc()
        for d, (lo, _hi) in enumerate(plan.slices):
            SHARD_INFLIGHT.labels(shard=str(d)).inc()
            self._shard_queues[d].put((job, d, by_start[lo], None, None))
        return ticket

    def _fold_shard_item(self, job: _BatchJob, d: int, payload) -> None:
        """Fold one shard's slice of one batch. The shard's accumulator is
        reassigned only after the fold returns, so an exception here leaves
        it consistent (the per-shard retry relies on that); failures after
        the accumulator handoff raise ``_UnsafeFoldError``."""
        with self._lock:
            plan = self._plan
        if job.kind == "wire":
            piece = payload
            if plan.native:
                # materialize THIS shard's slice of the unpack output (the
                # host kernel reads host memory); other shards keep folding
                piece = np.asarray(piece)  # lint: sync-ok
            plan.fold_shard(d, piece)
            return
        packed = job.kind == "packed"
        if plan.native:
            if packed:
                plan.fold_shard_packed(d, payload)
            else:
                plan.fold_shard(d, payload)
            return
        import jax

        with plan._device_dispatch_lock:
            # host-side transfer enqueue only — the copy itself proceeds
            # async and the barrier below stays outside the lock (packed
            # staging: only bpn-byte planes cross here, the unpack runs
            # in-graph on the shard's device)
            staged = jax.device_put(payload, plan.devices[d])
        if packed:
            plan.fold_shard_packed(d, staged)
        else:
            plan.fold_shard(d, staged)
        try:
            # the per-shard transfer out of the ring buffer must complete
            # before reuse; the fold itself stays in flight behind it
            jax.block_until_ready(staged)  # lint: sync-ok
        except BaseException as e:
            raise _UnsafeFoldError() from e

    def _retry_shard(self, job: _BatchJob, d: int, payload, first: BaseException) -> bool:
        """Per-shard leg of the degradation ladder: the failed shard's
        accumulator is provably untouched, so retry ITS slice once
        synchronously (the other shards' slices of this batch fold
        normally — the commit barrier keeps the accounting consistent) and
        flip the whole pipeline to the sync path. A second failure loses
        the batch and poisons permanently."""
        logger.warning(
            "streaming shard %d fold failed at batch %d (%s: %s); retrying on "
            "this shard and degrading the pipeline",
            d,
            job.seq,
            type(first).__name__,
            first,
        )
        with self._lock:
            self._degraded = True
            job.retried = True
        DEGRADED.set(1)
        DEGRADATIONS.inc()
        try:
            self._fold_shard_item(job, d, payload)
            return True
        except BaseException as second:
            unsafe = isinstance(second, _UnsafeFoldError)
            cause = (second.__cause__ or second) if unsafe else second
            cause.__context__ = first
            self._poison(cause, job.seq)
            logger.exception(
                "streaming shard %d lost batch %d; pipeline poisoned", d, job.seq
            )
            return False

    def _process_shard(self, item: tuple) -> None:
        """One shard worker's fold of its slice of one batch, with the
        per-shard degradation ladder and the cross-shard commit handoff."""
        job, d, payload, ring, buf = item
        t0 = time.monotonic()
        failed = False
        try:
            with trace.get_tracer().span(
                SPAN_FOLD, batch=job.seq, shard=d, kind=job.kind, k=job.k
            ) as fold_span:
                try:
                    with self._lock:
                        poisoned = self._error is not None
                    if poisoned:
                        # the pipeline is already lost: drop the fold (the
                        # shards are inconsistent either way), release
                        # resources fast
                        failed = True
                        return
                    try:
                        maybe_fail("streaming.fold")
                        maybe_fail(f"streaming.shard{d}.fold")
                        self._fold_shard_item(job, d, payload)
                    except BaseException as first:
                        if isinstance(first, _UnsafeFoldError):
                            cause = first.__cause__ or first
                            self._poison(cause, job.seq)
                            failed = True
                            logger.exception(
                                "streaming shard %d fold of batch %d failed "
                                "post-dispatch; pipeline poisoned",
                                d,
                                job.seq,
                            )
                        else:
                            failed = not self._retry_shard(job, d, payload, first)
                finally:
                    if ring is not None:
                        ring.release(buf)
                    dt = time.monotonic() - t0
                    with self._lock:
                        self._shard_fold_seconds[d] += dt
                        # D workers run concurrently: credit the global fold
                        # leg 1/D of each worker's wall so the overlap ratio
                        # keeps its single-pipeline meaning
                        self._fold_seconds += dt / self._n_shards
                    SHARD_INFLIGHT.labels(shard=str(d)).dec()
                    fold_span.set(outcome="failed" if failed else "folded")
        finally:
            # the commit barrier runs AFTER this shard's fold span closed:
            # when the LAST shard settles a failed batch, every shard span
            # of the batch is already in the ring the flight dump snapshots
            self._shard_job_done(job, failed)

    def _shard_job_done(self, job: _BatchJob, failed: bool) -> None:
        """Per-batch commit barrier: the LAST shard to finish settles the
        accounting — planar batches credit ``nb_models`` and leave flight
        atomically (or just leave flight when the batch failed); wire
        batches release the shared byte buffer once the mesh transfer
        completed (their credit waits for drain's acceptance sync)."""
        with self._lock:
            if failed:
                job.failed = True
            job.remaining -= 1
            last = job.remaining == 0
            if last and job.kind != "wire":  # planar AND packed batches
                self._in_flight_models -= job.k
                if not job.failed:
                    self.agg.nb_models += job.k
        if not last:
            return
        if job.global_release is not None:
            ring, buf = job.global_release
            job.global_release = None
            try:
                # commit-tail accesses: only the LAST shard (remaining hit
                # zero under the lock above) executes this branch, so the
                # job is single-owner here — ownership handoff through the
                # counter, not mutual exclusion
                if job.staged is not None and not job.failed:  # lint: guarded-ok: last-shard tail, single owner
                    import jax

                    # the wire bytes must be fully consumed by the mesh
                    # before the host buffer recycles
                    jax.block_until_ready(job.staged)  # lint: sync-ok
            except BaseException as e:
                self._poison(e, job.seq)
                job.failed = True  # lint: guarded-ok: last-shard tail, single owner
            finally:
                job.staged = None
                ring.release(buf)
        self._slot_release()
        INFLIGHT_FOLDS.dec()
        failed = job.failed  # lint: guarded-ok: last-shard tail, single owner
        retried = job.retried  # lint: guarded-ok: last-shard tail, single owner
        outcome = "failed" if failed else ("folded-degraded" if retried else "folded")
        # the commit barrier as a zero-width marker span: WHEN the batch
        # settled its accounting, and how (the last shard records it)
        trace.get_tracer().record_span(
            SPAN_COMMIT,
            start=time.monotonic(),
            duration=0.0,
            batch=job.seq,
            outcome=outcome,
        )
        BATCHES_TOTAL.labels(stage=outcome).inc()
        if failed:
            with self._lock:
                cause, pseq = self._error, self._poison_seq
            if cause is not None:
                self._flight_poison(cause, pseq)

    def _fold_pinned_stack(self, plan, stacked, k: int) -> None:
        """Fold ONE batch-sharding-pinned device batch through the shard
        plan on the caller's thread and credit ``nb_models`` under the
        lock — the per-shard fan-out idiom shared by the stacked and
        row-chunked caller-thread paths (one copy, not three: the
        ``by_start`` shard addressing and the credit ordering are exactly
        the PR-7-hardened sequence a missed divergent copy would break)."""
        self._slot_acquire()
        try:
            if plan.native:
                full = np.asarray(stacked)  # lint: sync-ok
                for d in range(plan.n_shards):
                    plan.fold_shard_slice(d, full)
            else:
                by_start = {
                    s.index[-1].start or 0: s.data for s in stacked.addressable_shards
                }
                for d, (lo, _hi) in enumerate(plan.slices):
                    plan.fold_shard(d, by_start[lo])
        finally:
            self._slot_release()
        with self._lock:
            self.agg.nb_models += k

    def _fold_planar_rows_now_sharded(self, rows: list) -> None:
        """Shard-parallel variant of :meth:`fold_planar_rows_now`: the rows
        are already device-resident mesh-sharded planars, so each shard
        folds its addressable piece of the stacked chunk on the CALLER's
        thread (deliberately synchronous, same rationale as the
        single-worker path: these rows already occupy device memory)."""
        self._join_shard_queues()
        err = self._poisoned()
        if err is not None:
            raise self._poison_error() from err
        if self._closed:
            raise StreamingError("pipeline is closed")
        import jax
        import jax.numpy as jnp

        agg = self.agg
        rows = list(rows)
        plan = self._ensure_plan(
            min(8, len(rows)), lambda: jnp.stack(rows[: min(8, len(rows))])
        )
        while rows:
            piece, rows = rows[:8], rows[8:]
            # pin the stacked chunk to the batch sharding: jnp.stack of
            # sharded rows does not guarantee the model-axis layout, and
            # the per-shard fan-out below reads addressable shards by their
            # column start
            stacked = jax.device_put(jnp.stack(piece), agg._batch_sharding)
            n_piece = len(piece)
            del piece
            self._fold_pinned_stack(plan, stacked, n_piece)

    def _drain_sharded(self) -> int:
        """The cross-shard barrier: every shard queue drains, the one
        deferred acceptance sync resolves the pending wire tickets, every
        shard's in-flight device folds complete, and the per-shard
        accumulators reassemble into the aggregator's global ``acc``."""
        self._join_shard_queues()
        # every worker is quiesced behind the queue join: the locked reads
        # below are for the discipline (and for late poisons from close())
        with self._lock:
            err = self._error
            plan = self._plan
        if err is not None:
            with self._lock:
                stale, self._pending = self._pending, []
                self._in_flight_models -= sum(t.k for t in stale)
            for ticket in stale:
                ticket._ok = None
            raise self._poison_error() from err
        with self._lock:
            pending, self._pending = self._pending, []
        accepted = 0
        try:
            for ticket in pending:
                ok_host = np.asarray(ticket._ok)
                ticket._ok = None
                ticket.accepted = ok_host
                accepted += int(ok_host.sum())
            if plan is not None:
                # per-shard completion barrier (device folds dispatch
                # asynchronously; their errors surface here, not in the
                # workers)
                plan.block_until_ready()
        except Exception as e:
            with self._lock:
                fresh = self._error is None
                self._error = e
                self._in_flight_models -= sum(t.k for t in pending)
            for ticket in pending:
                ticket._ok = None
            if fresh:
                self._flight_poison(e, None)
            raise self._poison_error() from e
        if pending:
            with self._lock:
                self.agg.nb_models += accepted
                self._in_flight_models -= sum(t.k for t in pending)
        # reduce-scatter: the plan PERSISTS across drain windows — the
        # per-shard accumulators stay authoritative (agg.acc reads
        # reassemble on demand; unmask subtracts per shard). The old
        # reassemble-here / re-decompose-next-window round trip (two full
        # accumulator copies per drain on native plans) is gone.
        self._publish_overlap()
        return accepted

    # -- eager per-shard unmask (docs/DESIGN.md §22) ------------------------

    def stage_unmask(self, mask_planar: np.ndarray) -> "_UnmaskJob | None":
        """Enqueue the round's unmask as per-shard tail jobs: each shard
        subtracts its mask slice as soon as ITS last queued fold commits,
        instead of after the global drain barrier plus a separate serial
        unmask pass. Returns ``None`` when the pipeline cannot run the
        eager path (not sharded, no live plan, degraded, or poisoned) —
        the caller falls back to the drain-time unmask. The returned job
        settles in :meth:`finish_unmask`."""
        with self._lock:
            plan = self._plan
            eligible = (
                self._sharded
                and plan is not None
                and not self._degraded
                and self._error is None
                and not self._closed
            )
        if not eligible:
            return None
        agg = self.agg
        out = np.empty((agg.model_length, agg.n_limbs), dtype=np.uint32)
        job = _UnmaskJob(mask_planar, out, self._n_shards)
        self._ensure_shard_workers()
        for d, q in enumerate(self._shard_queues):
            q.put((job, d))
        return job

    def _process_unmask(self, item: tuple) -> None:
        """One shard worker's eager unmask leg: runs after the shard's
        last fold (queue FIFO), subtracts that shard's mask slice, and
        records the hidden seconds as an ``overlap.eager_unmask`` span
        (home phase ``unmask``) so the timeline fold measures them as
        negative slack."""
        job, d = item
        t0 = time.monotonic()
        try:
            with self._lock:
                plan = self._plan
                poisoned = self._error is not None
            if not poisoned and plan is not None:
                self.agg.unmask_shard(plan, d, job.mask_planar, job.out)
                trace.get_tracer().record_span(
                    SPAN_EAGER_UNMASK,
                    start=t0,
                    duration=time.monotonic() - t0,
                    phase="unmask",
                    shard=d,
                    tenant=self.tenant,
                )
            elif job.error is None:
                with self._lock:
                    if job.error is None:
                        job.error = self._error or StreamingError(
                            "eager unmask skipped: plan gone"
                        )
        except BaseException as e:
            # the subtract is functional — the shard accumulator is
            # untouched on failure, so the caller's fallback to the
            # drain-time unmask pass stays byte-correct
            with self._lock:
                if job.error is None:
                    job.error = e
        finally:
            with self._lock:
                job.remaining -= 1
                last = job.remaining == 0
            if last:
                job.done.set()

    def finish_unmask(self, job: "_UnmaskJob") -> np.ndarray | None:
        """Settle an eager unmask: wait for every shard's tail job (most
        of the work has already run, hidden behind the fold/drain wall),
        then hand back the assembled host wire result — or ``None`` if any
        shard failed (caller falls back to the drain-time pass). Records
        the same ``unmask`` kernel op and gather accounting as the
        drain-time pass — what shrinks is the measured wall, which is
        exactly the point."""

        def settle():
            job.done.wait()
            with self._lock:
                err = job.error
            if err is not None:
                logger.warning(
                    "eager unmask fell back to the drain-time pass: %s: %s",
                    type(err).__name__,
                    err,
                )
                return None
            BYTES_REDUCED.labels(path="gather").inc(job.out.nbytes)
            return np.ascontiguousarray(job.out)

        return profiling.timed_kernel("unmask", self.agg.padded_length, settle)
