"""Streaming aggregation: bounded producer/consumer over the sharded fold.

``ShardedAggregator``'s batch entry points serialize the three legs of every
fold — host staging (pad + transpose + ``device_put``), the fold dispatch,
and (on the wire path) a blocking acceptance-vector fetch — so the host and
the device take turns idling. This module turns that into a pipeline:

- **staging buffer ring** — a small set of pre-allocated host buffers;
  batch N+1 is padded/copied into a ring buffer while batch N folds, and
  the per-batch ``np.pad``/``np.stack`` allocations (plus their page-fault
  tax, ~0.15 s per 200 MB at 25M params) disappear entirely. A buffer is
  reused only after the fold that consumed it has finished reading host
  memory (for device kernels: after the ``device_put`` transfer is
  complete; for the native host kernel: after the fold call returns).
- **dispatch-ahead depth** — up to ``dispatch_ahead`` batches are queued to
  a single fold worker thread, so XLA's asynchronous dispatch keeps
  multiple folds in flight behind one another while the producer stages
  ahead (DrJAX-style MapReduce pipelining, arxiv 2403.07128).
- **deferred acceptance syncs** — wire batches collect their ``ok`` arrays
  as in-flight device values; ``drain()`` fetches them all in ONE sync at
  flush/phase end instead of one blocking ``np.asarray(ok)`` per batch.
  Per-member accept/reject semantics and ``nb_models`` are byte-identical
  to the sequential path — invalid updates are zeroed inside the fold
  either way, and the deferred fetch only moves *when* the host learns the
  verdict, never what it is.

Fold order is FIFO (single worker), and the lazy-carry fold is an exact
modular sum, so the aggregate is byte-identical to sequential
``add_batch``/``add_wire_batch`` calls over the same updates regardless of
how far the pipeline runs ahead.

**Degradation ladder (streaming -> sync -> fail).** A fold failure in the
worker does NOT immediately poison the round: the accumulator is only
reassigned after a fold returns, so the failed batch is retried once
*synchronously*; on success the pipeline switches to the synchronous fold
path for the rest of the round (submits fold on the caller's thread,
logged + ``xaynet_streaming_degraded``) — the round completes with the
exact same aggregate, just without overlap. Only when the synchronous
retry ALSO fails is the pipeline poisoned — permanently, because the
batch's updates are lost and the accumulator no longer corresponds to any
consistent update set. Every poisoned-pipeline error names the poisoning
batch index and the original exception. Failures surfacing at ``drain()``
(XLA's asynchronous dispatch) skip the retry: the accumulator may already
reference the failed computation, so no consistent retry exists.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
import weakref

import numpy as np

from ..ops.fold_jax import MAX_LAZY_BATCH
from ..resilience.faults import maybe_fail
from ..telemetry.registry import get_registry
from .aggregator import ShardedAggregator

logger = logging.getLogger(__name__)

_registry = get_registry()
STAGING_DEPTH = _registry.gauge(
    "xaynet_streaming_staging_depth",
    "Staging ring buffers currently owned by in-flight batches.",
)
INFLIGHT_FOLDS = _registry.gauge(
    "xaynet_streaming_inflight_folds",
    "Fold batches submitted to the streaming pipeline and not yet folded.",
)
OVERLAP_RATIO = _registry.gauge(
    "xaynet_streaming_overlap_ratio",
    "Fraction of the shorter pipeline leg (staging vs folding) that ran "
    "concurrently with the other leg during the last drain window "
    "(1 = perfect overlap, 0 = fully serialized).",
)
BATCHES_TOTAL = _registry.counter(
    "xaynet_streaming_batches_total",
    "Streaming pipeline batches, by stage (staged = submitted, "
    "folded = fold completed).",
    ("stage",),
)
DEGRADED = _registry.gauge(
    "xaynet_streaming_degraded",
    "1 while the streaming pipeline has degraded to the synchronous fold "
    "path after a fold failure (resets with the next pipeline).",
)
DEGRADATIONS = _registry.counter(
    "xaynet_streaming_degradations_total",
    "Times a streaming pipeline degraded to the synchronous fold path.",
)

_SHUTDOWN = object()


class StreamingError(RuntimeError):
    """The fold pipeline failed; the aggregate is unusable."""


class _UnsafeFoldError(Exception):
    """A fold failed at a point where the accumulator may already have been
    reassigned (post-dispatch transfer wait / acceptance fetch): no
    consistent synchronous retry exists, the pipeline must poison.
    ``__cause__`` is the real failure. ``settled`` is True when the batch's
    in-flight count was already handed off (planar ``_credit`` ran) so the
    poison handler must not subtract it again."""

    def __init__(self, settled: bool = False):
        super().__init__()
        self.settled = settled


class StreamTicket:
    """Handle for one submitted batch.

    ``accepted`` resolves at the next ``drain()``: a ``bool[K]`` per-member
    acceptance vector for wire batches, all-True for pre-validated planar
    batches. (In degraded/sync mode it resolves at submit time.)
    """

    __slots__ = ("k", "accepted", "_ok")

    def __init__(self, k: int):
        self.k = k
        self.accepted: np.ndarray | None = None
        self._ok = None  # in-flight device acceptance vector (wire batches)


class _StagingRing:
    """Fixed pool of pre-allocated host staging buffers.

    ``acquire`` blocks while every buffer is owned by an in-flight batch —
    this is the pipeline's memory bound (the producer can run at most
    ``size`` batches ahead of the fold worker).
    """

    def __init__(self, size: int, shape: tuple, dtype):
        self._free: queue_mod.Queue = queue_mod.Queue()
        self.size = size
        for _ in range(size):
            self._free.put(np.zeros(shape, dtype=dtype))

    def acquire(self, timeout: float | None = None) -> np.ndarray:
        buf = self._free.get(timeout=timeout)
        STAGING_DEPTH.inc()
        return buf

    def release(self, buf: np.ndarray) -> None:
        STAGING_DEPTH.dec()
        self._free.put(buf)


def _worker_main(ref: "weakref.ref[StreamingAggregator]", q: queue_mod.Queue) -> None:
    """Fold worker loop. Holds NO strong reference to the pipeline between
    items: an abandoned pipeline (e.g. a round that died before drain) is
    garbage-collected normally, and its ``weakref.finalize`` wakes this
    thread with the shutdown sentinel so it exits instead of leaking."""
    while True:
        item = q.get()
        try:
            if item is _SHUTDOWN:
                return
            self = ref()
            if self is None:
                return
            self._process(item)
            del self
        finally:
            q.task_done()


class StreamingAggregator:
    """Bounded streaming front-end over a :class:`ShardedAggregator`.

    One fold worker consumes staged batches FIFO; the caller's thread only
    stages. ``submit_*`` may block — on the staging ring when the producer
    is ``staging_buffers`` batches ahead, on the dispatch queue when it is
    ``dispatch_ahead`` folds ahead — which is the pipeline's backpressure.
    ``drain()`` waits for in-flight work, performs the one deferred
    acceptance sync, credits ``nb_models`` for wire batches, and publishes
    the overlap ratio.

    NOT thread-safe for concurrent producers: submits must come from one
    thread at a time (the coordinator's executor serializes them; tests and
    the bench are single-producer by construction).
    """

    def __init__(
        self,
        agg: ShardedAggregator,
        staging_buffers: int = 3,
        dispatch_ahead: int = 2,
        max_batch: int = 64,
    ):
        if staging_buffers < 2:
            raise ValueError("staging_buffers must be >= 2 (no overlap below that)")
        if dispatch_ahead < 1:
            raise ValueError("dispatch_ahead must be >= 1")
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.agg = agg
        self.staging_buffers = staging_buffers
        self.dispatch_ahead = dispatch_ahead
        self.max_batch = min(max_batch, MAX_LAZY_BATCH)
        self._queue: queue_mod.Queue = queue_mod.Queue(maxsize=dispatch_ahead)
        self._rings: dict[str, _StagingRing] = {}  # lazy: planar / wire
        self._pending: list[StreamTicket] = []  # wire tickets awaiting ok sync
        self._in_flight_models = 0  # submitted, not yet folded (upper bound)
        self._error: BaseException | None = None
        self._poison_seq: int | None = None  # batch index that poisoned us
        self._degraded = False  # sync fold path for the rest of the round
        self._batch_seq = 0  # submit-order index (poisoning diagnostics)
        self._worker: threading.Thread | None = None
        self._closed = False
        self._lock = threading.Lock()  # worker-shared counters/pending
        # a fresh pipeline is never degraded — reset the gauge here, not
        # only in close(): a degraded pipeline abandoned on phase failure
        # must not leave the gauge stuck at 1 for later healthy rounds
        DEGRADED.set(0)
        # overlap accounting, reset per drain window
        self._stage_seconds = 0.0
        self._fold_seconds = 0.0
        self._window_start: float | None = None

    # -- lifecycle ---------------------------------------------------------

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=_worker_main,
                args=(weakref.ref(self), self._queue),
                name="xn-stream-fold",
                daemon=True,
            )
            self._worker.start()
            # wake the worker if this pipeline is dropped without close()
            weakref.finalize(self, self._queue.put, _SHUTDOWN)

    def close(self) -> None:
        """Drain, then stop the fold worker. Idempotent. A poisoned
        pipeline (worker failure) still shuts down — the error has already
        surfaced (or will) through drain()/submit, and close() is the
        cleanup path."""
        if self._closed:
            return
        try:
            self.drain()
        except StreamingError:
            logger.warning("closing poisoned streaming pipeline")
        self._closed = True
        if self._degraded:
            DEGRADED.set(0)
        if self._worker is not None and self._worker.is_alive():
            self._queue.put(_SHUTDOWN)
            self._worker.join(timeout=60.0)

    # -- producer side -----------------------------------------------------

    @property
    def in_flight_models(self) -> int:
        """Submitted-but-uncredited update count (an upper bound for wire
        batches until their acceptance vector syncs at drain)."""
        with self._lock:
            return self._in_flight_models

    def counted_models(self) -> int:
        """``in_flight + agg.nb_models`` read atomically with the worker's
        per-batch handoff (credit nb_models / drop in-flight under the same
        lock), so a caller's capacity check (TooManyModels) never sees a
        batch double-counted mid-fold or dropped between fold and drain."""
        with self._lock:
            return self._in_flight_models + self.agg.nb_models

    @property
    def degraded(self) -> bool:
        """True once a fold failure switched the pipeline to the
        synchronous fold path (the round still completes)."""
        return self._degraded

    def _ring(self, kind: str) -> _StagingRing:
        ring = self._rings.get(kind)
        if ring is None:
            agg = self.agg
            if kind == "planar":
                shape = (self.max_batch, agg.n_limbs, agg.padded_length)
                dtype = np.uint32
            else:  # raw wire bytes
                shape = (self.max_batch, agg.padded_length * agg.config.bytes_per_number)
                dtype = np.uint8
            ring = self._rings[kind] = _StagingRing(self.staging_buffers, shape, dtype)
        return ring

    def _poison_error(self) -> StreamingError:
        """The sticky error, always naming the poisoning batch and cause."""
        cause = self._error
        seq = self._poison_seq
        where = f"batch {seq}" if seq is not None else "deferred sync"
        return StreamingError(
            f"streaming pipeline poisoned at {where}: "
            f"{type(cause).__name__}: {cause}"
        )

    def _check(self, k: int) -> None:
        if self._closed:
            raise StreamingError("pipeline is closed")
        if self._error is not None:
            raise self._poison_error() from self._error
        if k > self.max_batch:
            raise ValueError(f"batch of {k} exceeds max_batch={self.max_batch}")
        if self._window_start is None:
            self._window_start = time.monotonic()

    def _dispatch(self, item: tuple) -> None:
        """Queue to the fold worker — or, once degraded, fold synchronously
        on the caller's thread (same math, no overlap)."""
        buf, payload, kind, k, ticket, seq = item
        with self._lock:
            self._in_flight_models += k
        BATCHES_TOTAL.labels(stage="staged").inc()
        if not self._degraded:
            self._ensure_worker()
            INFLIGHT_FOLDS.inc()
            self._queue.put(item)
            return
        t0 = time.monotonic()
        try:
            # serialize with the worker: batches queued BEFORE degradation
            # (including the retry that flipped the flag) must finish before
            # a caller-thread fold touches agg.acc — two unsynchronized
            # mutators would lose updates
            self._queue.join()
            if self._error is not None:
                raise self._poison_error() from self._error
            self._fold_payload(payload, kind, k, ticket, defer_ok=False)
        except StreamingError:
            # already-poisoned pipeline: this batch just leaves flight
            with self._lock:
                self._in_flight_models -= k
            BATCHES_TOTAL.labels(stage="failed").inc()
            raise
        except BaseException as e:
            unsafe = isinstance(e, _UnsafeFoldError)
            cause = (e.__cause__ or e) if unsafe else e
            with self._lock:
                self._error = cause
                self._poison_seq = seq
                if not (unsafe and e.settled):
                    self._in_flight_models -= k
            BATCHES_TOTAL.labels(stage="failed").inc()
            raise self._poison_error() from cause
        finally:
            self._ring(kind).release(buf)
            with self._lock:
                self._fold_seconds += time.monotonic() - t0
        BATCHES_TOTAL.labels(stage="folded").inc()

    def submit_batch(self, stack: np.ndarray) -> StreamTicket:
        """Stage + stream-fold wire-layout ``uint32[K, model_len, L]``
        updates (the pre-validated path: all members count immediately)."""
        stack = np.asarray(stack, dtype=np.uint32)
        if stack.ndim != 3 or stack.shape[2] != self.agg.n_limbs:
            raise ValueError("expected uint32[K, model_len, L]")
        if stack.shape[1] != self.agg.model_length:
            raise ValueError("model length mismatch")
        k = stack.shape[0]
        self._check(k)
        t0 = time.monotonic()
        buf = self._ring("planar").acquire()
        # transpose+pad straight into the ring buffer (numpy strided copy,
        # no wire_to_planar intermediate): per-batch host allocation in the
        # steady state is zero
        view = buf[:k]
        view[:, :, : self.agg.model_length] = stack.transpose(0, 2, 1)
        if self.agg.padded_length != self.agg.model_length:
            view[:, :, self.agg.model_length :] = 0
        ticket = StreamTicket(k)
        self._stage_seconds += time.monotonic() - t0
        self._batch_seq += 1
        self._dispatch((buf, view, "planar", k, ticket, self._batch_seq))
        return ticket

    def fold_planar_rows_now(self, rows: list) -> None:
        """Fold already device-resident, validity-checked planar
        ``[L, padded_len]`` updates on the CALLER's thread (the wire-ingest
        server path: validated planars cached by ``validate_wire_update(s)``).

        Deliberately NOT queued: these rows already occupy device memory,
        so parking them behind ``dispatch_ahead`` would pin up to
        ``dispatch_ahead + 1`` full batches in HBM (~13 GB each at
        25M/batch 64) — and XLA's own asynchronous dispatch already
        overlaps device-side folds without our queue. Waits out queued
        work first (``agg.acc`` has exactly one mutator at a time), then
        stacks + folds in chunks, dropping consumed references, so peak
        device memory stays at the staged rows + one chunk-sized copy —
        the same bound as the pre-streaming flush."""
        if not rows:
            return
        self._queue.join()
        if self._error is not None:
            raise self._poison_error() from self._error
        if self._closed:
            raise StreamingError("pipeline is closed")
        import jax
        import jax.numpy as jnp

        agg = self.agg
        rows = list(rows)
        while rows:
            piece, rows = rows[:8], rows[8:]
            staged = jax.device_put(jnp.stack(piece), agg._batch_sharding)
            n_piece = len(piece)
            del piece
            agg.acc = agg._fold(agg.acc, staged)
            with self._lock:
                agg.nb_models += n_piece

    def submit_host_planar_rows(self, rows: list) -> StreamTicket:
        """Stream-fold host planar ``[L, padded_len]`` rows (numpy), copied
        into a ring buffer here so the caller can recycle its arrays."""
        k = len(rows)
        if k == 0:
            raise ValueError("empty planar batch")
        self._check(k)
        t0 = time.monotonic()
        buf = self._ring("planar").acquire()
        view = buf[:k]
        for i, row in enumerate(rows):
            np.copyto(view[i], row)
        ticket = StreamTicket(k)
        self._stage_seconds += time.monotonic() - t0
        self._batch_seq += 1
        self._dispatch((buf, view, "planar", k, ticket, self._batch_seq))
        return ticket

    def submit_wire_batch(self, raw: np.ndarray) -> StreamTicket:
        """Stage + stream-fold RAW wire element blocks
        ``uint8[K, model_len * bpn]``. Acceptance is DEFERRED: the per-member
        ``bool[K]`` lands on the ticket at the next ``drain()`` (the fold
        itself excludes invalid members either way)."""
        agg = self.agg
        bpn = agg.config.bytes_per_number
        raw = np.asarray(raw)
        if raw.dtype != np.uint8 or raw.ndim != 2 or raw.shape[1] != agg.model_length * bpn:
            raise ValueError("expected uint8[K, model_len * bytes_per_number]")
        k = raw.shape[0]
        self._check(k)
        t0 = time.monotonic()
        buf = self._ring("wire").acquire()
        view = buf[:k]
        view[:, : raw.shape[1]] = raw
        if agg.padded_length != agg.model_length:
            view[:, raw.shape[1] :] = 0  # zero bytes decode to zero elements
        ticket = StreamTicket(k)
        self._stage_seconds += time.monotonic() - t0
        self._batch_seq += 1
        self._dispatch((buf, view, "wire", k, ticket, self._batch_seq))
        return ticket

    # -- fold worker -------------------------------------------------------

    def _credit(self, staged, k: int) -> None:
        """Fold a planar batch and hand its count over atomically: the
        nb_models credit and the in-flight drop happen under one lock, so
        ``counted_models()`` never observes the batch twice (double count →
        spurious TooManyModels near the cap) or zero times."""
        agg = self.agg
        new_acc = agg._fold(agg.acc, staged)
        with self._lock:
            agg.acc = new_acc
            agg.nb_models += k
            self._in_flight_models -= k

    def _fold_payload(self, payload, kind: str, k: int, ticket, defer_ok: bool) -> None:
        """Fold one staged batch. ``defer_ok=True`` (worker path) leaves a
        wire batch's acceptance vector in flight for drain's single sync;
        ``defer_ok=False`` (degraded sync path) resolves it immediately.

        Failure classes matter here: the accumulator is reassigned only
        when a fold call RETURNS, so an exception raised before/inside the
        fold leaves ``agg.acc`` consistent (the degrade path may retry the
        batch). Failures after that point — the ring-buffer transfer wait
        and the acceptance fetch — are wrapped in ``_UnsafeFoldError``:
        retrying them would double-fold the batch."""
        import jax

        agg = self.agg
        if kind == "wire":
            staged = jax.device_put(payload, agg._batch_bytes_sharding)
            ok = agg.dispatch_staged_bytes(staged)
            # -- acc now references this batch: no retry beyond this line --
            if defer_ok:
                ticket._ok = ok
                with self._lock:
                    self._pending.append(ticket)
                try:
                    # the transfer out of the ring buffer must complete
                    # before reuse; the fold itself stays in flight behind it
                    jax.block_until_ready(staged)
                except BaseException as e:
                    with self._lock:
                        if ticket in self._pending:
                            self._pending.remove(ticket)
                    ticket._ok = None
                    raise _UnsafeFoldError() from e
                return
            try:
                ok_host = np.asarray(ok)  # acceptance sync (and fold barrier)
            except BaseException as e:
                raise _UnsafeFoldError() from e
            ticket.accepted = ok_host
            with self._lock:
                agg.nb_models += int(ok_host.sum())
                self._in_flight_models -= k
            return
        agg._resolve_kernel_cheap(k)
        if agg.kernel_used == "native-u64":
            # host fold reads the ring buffer directly (synchronous)
            # — no device staging at all
            self._credit(payload, k)
        else:
            staged = jax.device_put(payload, agg._batch_sharding)
            self._credit(staged, k)
            try:
                jax.block_until_ready(staged)  # host buffer free to reuse
            except BaseException as e:
                # _credit already handed the count off: settled
                raise _UnsafeFoldError(settled=True) from e
        ticket.accepted = np.ones(k, dtype=bool)

    def _degrade_and_retry(self, payload, kind: str, k: int, ticket, seq: int,
                           first: BaseException) -> str:
        """First fold failure with a consistent accumulator: switch the
        pipeline to the synchronous path and retry the batch once. Returns
        the outcome label; a second failure poisons permanently."""
        logger.warning(
            "streaming fold failed at batch %d (%s: %s); retrying on the "
            "synchronous path and degrading the pipeline",
            seq,
            type(first).__name__,
            first,
        )
        with self._lock:
            self._degraded = True
        DEGRADED.set(1)
        DEGRADATIONS.inc()
        try:
            self._fold_payload(payload, kind, k, ticket, defer_ok=False)
            return "folded-degraded"
        except BaseException as second:
            # the batch is lost: the accumulator no longer matches any
            # consistent update set — poison permanently, with the batch
            # index and root cause on every later error
            unsafe = isinstance(second, _UnsafeFoldError)
            cause = (second.__cause__ or second) if unsafe else second
            cause.__context__ = first
            with self._lock:
                self._error = cause
                self._poison_seq = seq
                if not (unsafe and second.settled):
                    self._in_flight_models -= k
            logger.exception("streaming fold batch %d lost; pipeline poisoned", seq)
            return "failed"

    def _process(self, item: tuple) -> None:
        """Worker-side fold with the degradation ladder: streaming fold ->
        one synchronous retry (switching the pipeline to sync mode) ->
        sticky poison naming the batch and the original exception."""
        buf, payload, kind, k, ticket, seq = item
        agg_t0 = time.monotonic()
        outcome = "folded"
        try:
            try:
                maybe_fail("streaming.fold")
                self._fold_payload(payload, kind, k, ticket, defer_ok=True)
            except BaseException as first:
                if isinstance(first, _UnsafeFoldError):
                    # acc may already reference the batch: retrying would
                    # double-fold it — poison straight away
                    cause = first.__cause__ or first
                    with self._lock:
                        self._error = cause
                        self._poison_seq = seq
                        if not first.settled:
                            self._in_flight_models -= k
                    outcome = "failed"
                    logger.exception(
                        "streaming fold batch %d failed post-dispatch; pipeline poisoned",
                        seq,
                    )
                else:
                    outcome = self._degrade_and_retry(payload, kind, k, ticket, seq, first)
        finally:
            if buf is not None:
                self._ring("wire" if kind == "wire" else "planar").release(buf)
            with self._lock:
                self._fold_seconds += time.monotonic() - agg_t0
            INFLIGHT_FOLDS.dec()
            # a failed fold is NOT folded: dashboards comparing staged vs
            # folded must be able to see the loss
            BATCHES_TOTAL.labels(stage=outcome).inc()

    # -- drain -------------------------------------------------------------

    def drain(self) -> int:
        """Wait for every in-flight fold, then perform the ONE deferred
        acceptance sync: fetch all pending ``ok`` vectors, resolve their
        tickets, credit ``nb_models``. Returns the number of updates
        accepted from deferred wire batches in this window."""
        self._queue.join()
        if self._error is not None:
            # the pipeline is poisoned — PERMANENTLY: once the degraded
            # retry has also failed the accumulator no longer corresponds
            # to any consistent update set, so every later drain (finalize,
            # close) must keep failing rather than let a snapshot with
            # missing/uncounted updates escape as a valid round result.
            # The deferred state is discarded once (stale tickets must not
            # resolve and their counts must leave flight).
            with self._lock:
                stale, self._pending = self._pending, []
                self._in_flight_models -= sum(t.k for t in stale)
            for ticket in stale:
                ticket._ok = None
            raise self._poison_error() from self._error
        with self._lock:
            pending, self._pending = self._pending, []
        accepted = 0
        try:
            for ticket in pending:
                ok_host = np.asarray(ticket._ok)
                ticket._ok = None
                ticket.accepted = ok_host
                accepted += int(ok_host.sum())
            # a true completion barrier: the worker only blocks on staged
            # INPUTS (ring-buffer reuse), so with profiling off the last
            # folds may still be executing behind XLA's async dispatch —
            # and their errors surface here, not in the worker
            import jax

            jax.block_until_ready(self.agg.acc)
        except Exception as e:
            # an asynchronously-dispatched fold failed (e.g. device OOM):
            # the accumulator may already reference the failed computation,
            # so no consistent synchronous retry exists — poison exactly
            # like an exhausted worker retry (drop the deferred counts and
            # keep every later drain failing)
            with self._lock:
                self._error = e
                self._in_flight_models -= sum(t.k for t in pending)
            for ticket in pending:
                ticket._ok = None
            raise self._poison_error() from e
        if pending:
            # the ONE deferred credit: the accepted count lands and the
            # optimistic in-flight count drops in the same locked step, so
            # counted_models() never dips (folded-but-uncredited) nor
            # double-counts
            with self._lock:
                self.agg.nb_models += accepted
                self._in_flight_models -= sum(t.k for t in pending)
        self._publish_overlap()
        return accepted

    def _publish_overlap(self) -> None:
        if self._window_start is None:
            return
        wall = max(time.monotonic() - self._window_start, 1e-9)
        shorter = min(self._stage_seconds, self._fold_seconds)
        if shorter > 0:
            overlap = (self._stage_seconds + self._fold_seconds - wall) / shorter
            OVERLAP_RATIO.set(max(0.0, min(1.0, overlap)))
        self._stage_seconds = 0.0
        self._fold_seconds = 0.0
        self._window_start = None
