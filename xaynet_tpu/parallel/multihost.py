"""Multi-host mesh initialization (pods over ICI/DCN).

The aggregation kernels are collective-free, so scaling to a multi-host pod
is purely a placement question: initialize the JAX distributed runtime,
build one global mesh, and keep using the same sharded aggregator. The
coordinator process runs on host 0; other hosts run ingest workers feeding
their local shard (staged work — see docs/ROADMAP.md).

    from xaynet_tpu.parallel.multihost import initialize, global_mesh
    initialize(coordinator_address="host0:1234", num_processes=4, process_id=i)
    mesh = global_mesh()
"""

from __future__ import annotations

from typing import Optional

import jax

from .mesh import make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the JAX distributed runtime (no-op for single-process)."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh():
    """A 1-D mesh over every device of every host (model-axis sharding)."""
    return make_mesh(jax.devices())


def local_slice(model_length: int) -> tuple[int, int]:
    """This host's contiguous [start, end) slice of the model axis.

    Ingest workers parse and stage only their slice of each wire update, so
    host->device traffic stays local to each host's ICI domain.
    """
    n_proc = jax.process_count()
    idx = jax.process_index()
    per = -(-model_length // n_proc)
    start = min(idx * per, model_length)
    return start, min(start + per, model_length)
