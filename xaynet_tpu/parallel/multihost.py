"""Multi-host sharded aggregation: each host ingests only its model slice.

The aggregation kernels are collective-free (elementwise over the model
axis), so a multi-host pod is a placement problem, not a communication
problem: initialize the JAX distributed runtime, build one global mesh over
every host's devices, and have each host parse + stage only ITS contiguous
slice of each wire update. ``jax.make_array_from_process_local_data``
assembles the per-host slices into one global sharded array with zero
cross-host transfers, and the same fold kernel runs SPMD on all hosts.

This replaces the reference's single-process in-memory accumulation
(rust/xaynet-server/src/state_machine/phases/update.rs:119-152) with a
design whose ingest bandwidth scales with the number of hosts.

Usage (one process per host, every process runs the same program):

    from xaynet_tpu.parallel.multihost import initialize, MultiHostAggregator
    initialize(coordinator_address="host0:1234", num_processes=N, process_id=i)
    agg = MultiHostAggregator(config, model_length)
    lo, hi = agg.local_slice           # this host's [lo, hi) of the model
    agg.add_local_batch(wire[:, lo:hi, :])
    out_local = agg.unmask_local(mask_wire[lo:hi, :])

Validated by a real 2-process CPU-mesh test (tests/test_multihost.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from ..core.mask.config import MaskConfig
from ..ops.fold_jax import p_mod_sub, wire_to_planar
from .aggregator import ShardedAggregator
from .mesh import make_mesh, shard_slices


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the JAX distributed runtime (no-op for single-process)."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh():
    """A 1-D mesh over every device of every host (model-axis sharding)."""
    return make_mesh(jax.devices())


class MultiHostAggregator:
    """Sharded aggregation where each process feeds only its model slice.

    Requires every process to contribute the same number of devices (the
    usual TPU pod shape). The padded model length divides evenly across
    devices, so each process owns a contiguous ``padded/num_processes``
    slice of the model axis.
    """

    def __init__(self, config: MaskConfig, model_length: int, mesh=None, kernel: str = "xla"):
        self.mesh = mesh if mesh is not None else global_mesh()
        n_proc = jax.process_count()
        n_local = len([d for d in self.mesh.devices.flat if d.process_index == jax.process_index()])
        if n_local * n_proc != self.mesh.devices.size:
            raise ValueError("every process must contribute the same number of devices")
        self.agg = ShardedAggregator(config, model_length, mesh=self.mesh, kernel=kernel)
        # a process's slice is the union of its devices' shard slices: the
        # same contiguous-column decomposition the shard-parallel streaming
        # fold uses per device (mesh.shard_slices), taken n_local at a time
        self._lo_padded, self._hi_padded = shard_slices(self.agg.padded_length, n_proc)[
            jax.process_index()
        ]
        self.n_limbs = self.agg.n_limbs
        self.model_length = model_length
        self._unmask_jit = jax.jit(
            p_mod_sub,
            static_argnames=("order",),
            out_shardings=self.agg._acc_sharding,
        )
        # the slice math above assumes this process's devices own the
        # CONTIGUOUS block [lo, hi) of the sharded axis (true for the
        # default process-major device order; NOT for arbitrary reordered
        # meshes, e.g. mesh_utils.create_device_mesh) — verify, don't assume
        starts = sorted(
            s.index[1].start
            for s in self.agg.acc.addressable_shards
        )
        width = self._hi_padded - self._lo_padded
        expect = list(range(self._lo_padded, self._hi_padded, width // len(starts)))
        if starts != expect:
            raise ValueError(
                "mesh device order interleaves processes: this process's "
                f"shards start at {starts}, expected the contiguous block "
                f"{expect}; use the default process-major device order"
            )

    @property
    def local_slice(self) -> tuple[int, int]:
        """This host's [lo, hi) of the REAL (unpadded) model axis."""
        return min(self._lo_padded, self.model_length), min(self._hi_padded, self.model_length)

    @property
    def nb_models(self) -> int:
        return self.agg.nb_models

    def _local_planar(self, local_wire: np.ndarray, batch: bool) -> np.ndarray:
        """Wire slice -> planar, padded to this host's padded slice width."""
        arr = np.asarray(local_wire, dtype=np.uint32)
        if not batch:
            arr = arr[None]
        lo, hi = self.local_slice
        if arr.shape[1] != hi - lo or arr.shape[2] != self.n_limbs:
            raise ValueError(
                f"expected uint32[K, {hi - lo}, {self.n_limbs}] (this host's slice)"
            )
        planar = wire_to_planar(arr)  # [K, L, slice]
        want = self._hi_padded - self._lo_padded
        if planar.shape[2] != want:
            planar = np.pad(planar, ((0, 0), (0, 0), (0, want - planar.shape[2])))
        return planar

    def add_local_batch(self, local_wire: np.ndarray) -> None:
        """Fold a batch given only this host's slice: ``uint32[K, hi-lo, L]``.

        Every process must call this collectively with the same K (SPMD).
        """
        planar = self._local_planar(local_wire, batch=True)
        k = planar.shape[0]
        global_shape = (k, self.n_limbs, self.agg.padded_length)
        staged = jax.make_array_from_process_local_data(
            self.agg._batch_sharding, planar, global_shape
        )
        self.agg.add_planar_batch(staged)

    def add_local_wire_batch(self, local_raw: np.ndarray) -> np.ndarray:
        """Fold RAW wire bytes given only this host's element slice:
        ``uint8[K, (hi-lo)*bpn]`` — the device-ingest path multihost.

        Each host ships the byte sub-block of the serialized element block
        covering its model slice (element-aligned by construction: the
        per-host slice is ``padded/num_processes`` whole elements), the
        global byte array assembles with zero cross-host transfers, and
        unpack + per-update validity + fold run SPMD. Every process must
        call this collectively with the same K. Returns the ``bool[K]``
        acceptance vector (identical on every process — validity reduces
        with a psum over the model axis)."""
        from ..ops.fold_jax import MAX_LAZY_BATCH

        bpn = self.agg.config.bytes_per_number
        raw = np.asarray(local_raw)
        lo, hi = self.local_slice
        if raw.dtype != np.uint8 or raw.ndim != 2 or raw.shape[1] != (hi - lo) * bpn:
            raise ValueError(f"expected uint8[K, {(hi - lo) * bpn}] (this host's wire slice)")
        if raw.shape[0] > MAX_LAZY_BATCH:
            raise ValueError("batch too large for lazy-carry fold")
        want = (self._hi_padded - self._lo_padded) * bpn
        if raw.shape[1] != want:
            raw = np.pad(raw, ((0, 0), (0, want - raw.shape[1])))
        global_shape = (raw.shape[0], self.agg.padded_length * bpn)
        staged = jax.make_array_from_process_local_data(
            self.agg._batch_bytes_sharding, raw, global_shape
        )
        return self.agg._ingest_staged_bytes(staged)

    def _assemble_local(self, arr: jax.Array) -> np.ndarray:
        """This process's addressable columns of a planar sharded array,
        cut to the real (unpadded) slice and returned in wire layout."""
        lo, hi = self.local_slice
        shards = sorted(arr.addressable_shards, key=lambda s: s.index[1].start)
        local = np.concatenate([np.asarray(s.data) for s in shards], axis=1)
        return np.ascontiguousarray(local[:, : hi - lo].T)

    def unmask_local(self, local_mask_wire: np.ndarray) -> np.ndarray:
        """Subtract the aggregated mask (this host's slice only) and return
        the unmasked wire slice ``uint32[hi-lo, L]``."""
        planar = self._local_planar(local_mask_wire, batch=False)[0]
        global_shape = (self.n_limbs, self.agg.padded_length)
        mask_dev = jax.make_array_from_process_local_data(
            self.agg._acc_sharding, planar, global_shape
        )
        return self._assemble_local(self._unmask_jit(self.agg.acc, mask_dev, self.agg.order))

    def snapshot_local(self) -> np.ndarray:
        """This host's wire-layout slice of the aggregate."""
        return self._assemble_local(self.agg.acc)
