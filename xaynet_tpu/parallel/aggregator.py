"""Sharded device-resident aggregation of masked updates.

The coordinator-side hot path (reference analogue:
rust/xaynet-server/src/state_machine/phases/update.rs:119-152, which does one
sequential big-int pass per accepted update). Here the running aggregate is
an HBM-resident **planar** ``uint32[L, padded_len]`` buffer sharded over the
model-length axis of a device mesh; incoming masked updates are staged into
``[K, L, padded_len]`` batches and folded in with the single-pass lazy-carry
kernel (``ops.fold_jax``) — one full read of the batch plus a handful of
tiny passes, no collectives (the length axis is embarrassingly parallel).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.mask.config import MaskConfig
from ..ops import limbs as host_limbs
from ..ops.fold_jax import (
    MAX_LAZY_BATCH,
    fold_packed_batch,
    fold_planar_batch,
    p_mod_sub,
    wire_to_planar,
)
from ..telemetry import profiling
from ..telemetry.registry import get_registry
from ..utils.kernels import FOLD_KERNELS
from .mesh import MODEL_AXIS, make_mesh, pad_to_multiple, shard_map_compat

logger = logging.getLogger(__name__)

# cross-shard combine traffic (bytes actually copied), by path: "scatter" =
# decomposing the global accumulator into per-shard buffers (native plans
# copy; device plans decompose zero-copy), "gather" = reassembling /
# materializing the accumulator on the host (the final model download and
# any snapshot/checkpoint read). The reduce-scatter layout keeps the
# accumulator per-shard ACROSS drain windows, so these counters advance
# once per round instead of twice per drain — the bench's bytes-moved
# series reads them.
BYTES_REDUCED = get_registry().counter(
    "xaynet_bytes_reduced_total",
    "Accumulator bytes copied on the cross-shard combine path, by "
    "direction (scatter = global -> per-shard, gather = per-shard -> "
    "global/host).",
    ("path",),
)

# same family streaming.py registers for its ring staging (the registry
# dedupes by name): the wire-ingest staging uploads are accounted here so
# the ingress bench can read bytes-moved-per-accepted-update straight off
# /metrics — "wire" = v1 interleaved element blocks, "wire-planar" = v2
# byte-planar blocks that stay packed through the fold (docs/DESIGN.md §21)
BYTES_STAGED = get_registry().counter(
    "xaynet_bytes_staged_total",
    "Bytes copied into host staging rings (and later across host->device), "
    "by layout: packed = byte-planar wire-width planes, unpacked = full "
    "uint32 limb planes, wire = raw serialized element blocks, "
    "wire-planar = v2 byte-planar element blocks staged packed.",
    ("layout",),
)

_unmask_kernel = jax.jit(p_mod_sub, static_argnames=("order",))


# the cross-version shard_map shim lives in mesh.py (one shim for every
# call site); the local alias keeps this module's call sites unchanged
_shard_map = shard_map_compat

# auto-calibration verdicts, process-wide: a long-running coordinator builds
# a fresh aggregator every round but the (backend, shape, order) question has
# the same answer every time
_AUTO_KERNEL_CACHE: dict[tuple, str] = {}

# compiled fold callables, process-wide. jit caches by FUNCTION IDENTITY, so
# a per-aggregator closure would retrace and leak one executable per round
# on a long-running coordinator (observed ~4 MB RSS/round in the pallas
# soak before this cache); keyed by everything the closure captures
_FOLD_FN_CACHE: dict[tuple, object] = {}


def _mesh_key(mesh) -> tuple:
    """Cache identity of a mesh: (axis shape, flat device ids).

    The ``Mesh`` object itself must NOT be the key: a coordinator that
    rebuilds its mesh every round (fresh ``make_mesh()`` per aggregator)
    would then grow the process-wide caches — and the compiled executables
    they hold — without bound, one entry per round, even though two meshes
    over the same devices in the same shape compile to the same program.
    """
    return (tuple(mesh.devices.shape), tuple(int(d.id) for d in mesh.devices.flat))


def _build_wire_unpack(bpn: int, order: int, multi_device: bool):
    """The ONE wire unpack + per-update validity + exclusion body, shared by
    the two-step and fused ingest builders so the accelerator-only fused
    path can never silently diverge from the CPU-tested two-step path.

    Runs inside jit (and, when ``multi_device``, inside shard_map, where the
    psum makes an update invalid on ANY shard excluded on every shard).
    """
    from ..ops import limbs_jax

    def unpack_mask(raw):
        count = raw.shape[-1] // bpn
        planar = limbs_jax.wire_bytes_to_planar(raw, count, bpn)
        ok = limbs_jax.planar_all_lt_const(planar, order)  # per update
        if multi_device:
            bad = jax.lax.psum((~ok).astype(jnp.uint32), MODEL_AXIS)
            ok = bad == jnp.uint32(0)
        planar = jnp.where(ok[:, None, None], planar, jnp.uint32(0))
        return planar, ok

    return unpack_mask


def _build_planar_ok(n_limbs: int, order: int, multi_device: bool):
    """Wire-v2 twin of ``_build_wire_unpack``, validity only: the input is
    already the byte-planar ``uint8[K, bpn, n]`` packed layout
    (serialization.py ``WIRE_PLANAR_FLAG``), so limb assembly reads
    contiguous planes (``limbs_jax.packed_planar_to_limbs``) — and only
    *transiently*, inside this jit. The caller keeps the packed bytes as
    the staged representation; no resident uint32 planar exists on the v2
    path until the fused packed fold. Same per-update validity + psum
    exclusion semantics as v1.
    """
    from ..ops import limbs_jax

    def check(raw):
        planar = limbs_jax.packed_planar_to_limbs(raw, n_limbs)
        ok = limbs_jax.planar_all_lt_const(planar, order)  # per update
        if multi_device:
            bad = jax.lax.psum((~ok).astype(jnp.uint32), MODEL_AXIS)
            ok = bad == jnp.uint32(0)
        return ok

    return check


def _sharded_native_fan_out(
    acc_np: np.ndarray,
    batch_np: np.ndarray,
    batch_dtype,
    slice_fold,
    batch_fold,
    n_shards: int,
    state: dict,
) -> np.ndarray:
    """Shared thread fan-out for the per-shard strided native folds: one
    concurrent kernel call per mesh shard over the full staged batch —
    shard ``d`` reads and writes only its contiguous plane slice of the
    shared acc/out buffers (disjoint columns, no synchronization beyond
    the join), each call under the per-shard thread budget. The GIL is
    released inside the C++ kernel, so the threads genuinely overlap the
    shard folds; they are spawned per call (spawn cost ~10us each, noise
    against a >=100ms fold) because the aggregator has no close() hook to
    own a pool's lifecycle. ``slice_fold(acc, batch, spare, lo, hi,
    budget) -> bool`` folds one shard's column slice; ``batch_fold(acc,
    batch, out) -> acc`` is the exact generic fallback when the native
    library becomes unavailable mid-round. Returns the new accumulator
    (``state['spare']`` reused when possible, exactly like the
    single-device ping-pong)."""
    import threading

    from .mesh import shard_slices
    from .shards import shard_thread_budget

    acc_c = np.ascontiguousarray(acc_np, dtype=np.uint32)
    batch_c = np.ascontiguousarray(batch_np, dtype=batch_dtype)
    spare = state["spare"]
    if not (
        spare is not None
        and spare.shape == acc_c.shape
        and spare.dtype == np.uint32
        and spare.flags.c_contiguous
        and spare is not acc_c
    ):
        spare = np.empty_like(acc_c)
    if not state["budget"]:
        state["budget"] = shard_thread_budget(n_shards)
    budget = state["budget"]
    slices = shard_slices(acc_c.shape[1], n_shards)
    results = [False] * n_shards
    errors: list[BaseException] = []

    def fold_slice(i: int, lo: int, hi: int) -> None:
        try:
            results[i] = slice_fold(acc_c, batch_c, spare, lo, hi, budget)
        except BaseException as e:  # surfaced after the join
            errors.append(e)

    threads = [
        threading.Thread(
            target=fold_slice, args=(i, lo, hi), name=f"xn-shard-fold-{i}", daemon=True
        )
        for i, (lo, hi) in enumerate(slices)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    if all(results):
        return spare
    return batch_fold(acc_c, batch_c, spare)


def _sharded_native_fold_packed(
    acc_np: np.ndarray, packed_np: np.ndarray, order_limbs, n_shards: int, state: dict
) -> np.ndarray:
    """Packed twin of :func:`_sharded_native_fold`: the shared fan-out
    over the strided packed-fold kernel (``ops.limbs.fold_packed_slice_host``)
    reading the byte-planar batch directly."""
    return _sharded_native_fan_out(
        acc_np,
        packed_np,
        np.uint8,
        lambda acc, packed, spare, lo, hi, budget: host_limbs.fold_packed_slice_host(
            acc, packed, spare, lo, hi, order_limbs, n_threads=budget
        ),
        # library unavailable mid-round: exact generic fallback (one unpack)
        lambda acc, packed, out: host_limbs.fold_packed_batch_host(
            acc, packed, order_limbs, out=out
        ),
        n_shards,
        state,
    )


def _sharded_native_fold(
    acc_np: np.ndarray, stack_np: np.ndarray, order_limbs, n_shards: int, state: dict
) -> np.ndarray:
    """The shared fan-out over the strided planar-fold kernel
    (``ops.limbs.fold_planar_slice_host``) reading the full host planar
    batch."""
    return _sharded_native_fan_out(
        acc_np,
        stack_np,
        np.uint32,
        lambda acc, stack, spare, lo, hi, budget: host_limbs.fold_planar_slice_host(
            acc, stack, spare, lo, hi, order_limbs, n_threads=budget
        ),
        # library unavailable mid-round: exact generic fallback
        lambda acc, stack, out: host_limbs.fold_planar_batch_host(
            acc, stack, order_limbs, out=out
        ),
        n_shards,
        state,
    )


class ShardedAggregator:
    """Accumulates masked updates on-device, sharded over the model axis.

    ``kernel`` picks the fold implementation: ``"xla"`` (``ops.fold_jax``),
    ``"pallas"`` (the fused VMEM kernel, ``ops.fold_pallas``),
    ``"pallas-interpret"`` (same kernel through the Pallas interpreter — the
    CI path that keeps the grid/BlockSpec layout continuously exercised
    without a Mosaic compiler), or ``"auto"``: on accelerator backends the
    first fold times XLA vs Pallas on the real staged batch and keeps the
    winner; on CPU it short-circuits to XLA (interpret-mode Pallas is an
    oracle, not a production kernel). The choice actually taken is reported
    in ``kernel_used``.
    """

    def __init__(
        self,
        config: MaskConfig,
        model_length: int,
        mesh=None,
        kernel: str = "xla",
    ):
        if kernel not in FOLD_KERNELS:
            raise ValueError(f"kernel must be one of {FOLD_KERNELS}, got {kernel!r}")
        self.kernel = kernel
        self.kernel_used: str | None = None  # resolved on first fold
        self._fold_fn = None  # built once kernel_used resolves
        self.config = config
        self.model_length = model_length
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        self.padded_length = pad_to_multiple(model_length, n_dev)
        self.n_limbs = host_limbs.n_limbs_for_order(config.order)
        self.order = config.order
        # planar shardings: model axis is the innermost (lane) dimension
        self._acc_sharding = NamedSharding(self.mesh, P(None, MODEL_AXIS))
        self._batch_sharding = NamedSharding(self.mesh, P(None, None, MODEL_AXIS))
        # raw wire bytes shard over the same model axis: padded_length is a
        # multiple of the mesh size, so every device's byte slice is
        # element-aligned (count/n elements x bpn bytes)
        self._batch_bytes_sharding = NamedSharding(self.mesh, P(None, MODEL_AXIS))
        # packed byte-planar staging batches [K, bpn, padded] shard over the
        # same model (lane) axis as the planar layout
        self._batch_packed_sharding = NamedSharding(self.mesh, P(None, None, MODEL_AXIS))
        # the single-source-of-truth pack width (ops/limbs.wire_width_for):
        # the streaming pipeline stages bpn bytes per element instead of
        # 4*L whenever that is actually narrower
        self.packed_width = host_limbs.wire_width_for(self.order)
        self._packed_fold_fn = None  # built once kernel_used resolves
        # reduce-scatter ownership: while a ShardPlan is adopted, the
        # per-shard buffers ARE the accumulator and `_acc` is stale — the
        # `acc` property reassembles on demand (the only gathers left are
        # explicit reads: snapshot/checkpoint/final download)
        self._live_plan = None
        self._acc = jax.device_put(
            jnp.zeros((self.n_limbs, self.padded_length), dtype=jnp.uint32), self._acc_sharding
        )
        self.nb_models = 0

    # -- reduce-scatter accumulator ownership -------------------------------

    @property
    def acc(self):
        """The global planar accumulator. With a live (adopted) shard plan
        the per-shard buffers are authoritative and this READ reassembles
        them on demand — zero-copy for device plans, one counted
        concatenation for native host plans. The reduce-scatter contract:
        nothing gathers per drain window anymore; only explicit reads
        (snapshot, checkpoint, the final model download) pay the gather."""
        plan = self._live_plan
        if plan is not None:
            return plan.reassemble()
        return self._acc

    @acc.setter
    def acc(self, value):
        # an explicit accumulator write (restore/reset/non-sharded fold)
        # supersedes any adopted plan — the per-shard buffers are stale
        if self._live_plan is not None:
            self._live_plan = None
        self._acc = value

    def adopt_plan(self, plan) -> None:
        """Adopt a :class:`~xaynet_tpu.parallel.shards.ShardPlan` as the
        authoritative accumulator (the streaming pipeline's reduce-scatter
        handoff). The plan persists across drain windows; ``acc`` reads
        reassemble on demand."""
        self._live_plan = plan

    def release_plan_pages(self) -> None:
        """Give the adopted plan's pool pages back (the round's unmask
        tail, docs/DESIGN.md §19) and drop the plan — the buffers may be
        re-leased to another tenant, so the accumulator must never be
        reassembled from them again."""
        plan = self._live_plan
        if plan is not None:
            self._live_plan = None
            plan.release_pages()

    def _to_planar_padded(self, stack: np.ndarray) -> np.ndarray:
        """Wire ``[K, n, L]`` -> planar padded ``[K, L, padded_len]`` (host)."""
        planar = wire_to_planar(stack)
        if self.padded_length != planar.shape[2]:
            planar = np.pad(planar, ((0, 0), (0, 0), (0, self.padded_length - planar.shape[2])))
        return planar

    def add_batch(self, stack) -> None:
        """Fold wire-layout ``uint32[K, model_len, L]`` updates into the aggregate.

        Zero padding columns are valid group elements, so padding never
        affects the real slice.
        """
        stack = np.asarray(stack, dtype=np.uint32)
        if stack.ndim != 3 or stack.shape[2] != self.n_limbs:
            raise ValueError("expected uint32[K, model_len, L]")
        if stack.shape[1] != self.model_length:
            raise ValueError("model length mismatch")
        if stack.shape[0] > MAX_LAZY_BATCH:
            raise ValueError("batch too large for lazy-carry fold")
        planar = self._to_planar_padded(stack)
        self._resolve_kernel_cheap(stack.shape[0])
        if self.kernel_used == "native-u64":
            # the host kernel reads the planar directly — staging it onto
            # the (CPU) jax device would only buy a copy
            self.acc = self._fold(self.acc, planar)
        else:
            staged = jax.device_put(planar, self._batch_sharding)
            self.acc = self._fold(self.acc, staged)
        self.nb_models += stack.shape[0]

    def add_planar_batch(self, stack_planar: jax.Array) -> None:
        """Fold an already device-resident planar ``[K, L, padded_len]`` batch."""
        self.acc = self._fold(self.acc, stack_planar)
        self.nb_models += stack_planar.shape[0]

    def _stage_raw_bytes(self, raw: np.ndarray):
        """Shared guard + pad + upload for raw wire element blocks: validate
        dtype/shape, zero-pad to the padded length (zero bytes decode to
        zero elements — valid and fold-neutral), and device_put with the
        element-aligned byte-axis sharding. Used by the batch ingest AND
        the per-update validate path so the two can never diverge."""
        bpn = self.config.bytes_per_number
        raw = np.asarray(raw)
        if raw.dtype != np.uint8 or raw.ndim != 2 or raw.shape[1] != self.model_length * bpn:
            raise ValueError("expected uint8[K, model_len * bytes_per_number]")
        if raw.shape[0] > MAX_LAZY_BATCH:
            raise ValueError("batch too large for lazy-carry fold")
        if self.padded_length != self.model_length:
            raw = np.pad(raw, ((0, 0), (0, (self.padded_length - self.model_length) * bpn)))
        BYTES_STAGED.labels(layout="wire").inc(raw.nbytes)
        return jax.device_put(raw, self._batch_bytes_sharding)

    def add_wire_batch(self, raw: np.ndarray) -> np.ndarray:
        """Fold RAW wire element blocks ``uint8[K, model_len * bpn]``.

        The device-ingest fast path: ships the serialized little-endian
        element block as-is (``bpn/(4 L)`` of the limb-tensor size — 75%
        for the 6-byte f32/M3 configs, 87.5% for 7-byte M6), then unpacks,
        validity-checks, and folds entirely on device — the coordinator
        never runs a host-side element parse (the second hot loop after
        the fold; reference parses per element, vect.rs:24-80).

        Validity is per update: an update with any element >= the group
        order is EXCLUDED from the fold (zeroed — the additive identity)
        and not counted in ``nb_models``, mirroring the reference's
        per-message rejection (the coordinator must reject it before its
        seed-dict insert). Returns the ``bool[K]`` acceptance vector.
        """
        return self._ingest_staged_bytes(self._stage_raw_bytes(raw))

    def validate_wire_update(self, raw: np.ndarray):
        """Unpack + validity-check ONE raw wire update on device.

        The coordinator's per-update validation step when wire ingest is on
        (reference ordering: validate BEFORE the seed-dict insert,
        update.rs:119-152). Returns the device-resident planar
        ``[L, padded_len]`` (already validity-masked) for later staging, or
        ``None`` if any element is >= the group order.
        """
        raw = np.asarray(raw)
        if raw.ndim != 1:
            raise ValueError("expected uint8[model_len * bytes_per_number]")
        return self.validate_wire_updates([raw])[0]

    def validate_wire_updates(self, raws) -> list:
        """Unpack + validity-check a GROUP of raw wire updates in ONE device
        round-trip: one staged upload, one unpack+validity dispatch, one
        acceptance-vector fetch — where the per-update path pays a full
        dispatch + blocking ``np.asarray(ok)`` sync per update. Semantics
        are per update and identical to ``validate_wire_update``: the
        returned list is parallel to ``raws``, holding the validity-masked
        device planar ``[L, padded_len]`` for accepted updates and ``None``
        for any whose element is >= the group order.
        """
        if not raws:
            return []
        block = np.stack([np.asarray(r) for r in raws])
        # bucket K to the next power of two: the unpack jit specializes on
        # the batch dimension, and coalescer linger timeouts produce ragged
        # group sizes — without bucketing every new K would stall the
        # update phase on a fresh XLA compile mid-round. Zero pad rows
        # decode to zero elements (valid group members) and are sliced off
        # below; at most log2(batch) programs ever compile.
        k = len(raws)
        bucket = min(1 << max(0, k - 1).bit_length(), MAX_LAZY_BATCH)
        if bucket > k:
            block = np.concatenate(
                [block, np.zeros((bucket - k, block.shape[1]), dtype=block.dtype)]
            )
        staged = self._stage_raw_bytes(block)
        planar, ok = profiling.timed_kernel(
            "wire_unpack",
            staged.shape[0] * self.padded_length,
            lambda: self._make_unpack_fn()(staged),
        )
        ok_host = np.asarray(ok)
        return [planar[i] if ok_host[i] else None for i in range(k)]

    def validate_planar_update(self, raw: np.ndarray):
        """Wire-v2: validity-check ONE byte-planar update
        (``uint8[bpn, model_len]``, the serialized planar element block
        viewed 2-D) on device. Same contract as ``validate_wire_update``,
        except the accepted row stays PACKED (``uint8[bpn, padded_len]``) —
        the uint32 limb expansion only ever happens transiently inside the
        validity/fold jits, never as a resident buffer."""
        raw = np.asarray(raw)
        if raw.ndim != 2:
            raise ValueError("expected uint8[bytes_per_number, model_len]")
        return self.validate_planar_updates([raw])[0]

    def validate_planar_updates(self, raws) -> list:
        """Wire-v2 twin of ``validate_wire_updates``: one staged upload +
        validity dispatch + acceptance fetch for a group of byte-planar
        element blocks. The upload IS the packed staging layout — no byte
        gather on either side of the transfer — and the returned rows are
        the staged PACKED device slices (``uint8[bpn, padded_len]``), so an
        accepted v2 update occupies ``bpn`` bytes/element until the packed
        fold consumes it, where the v1 path parks a ``4L``-byte planar.
        ``None`` marks members with an element >= the group order.
        """
        if not raws:
            return []
        bpn = self.config.bytes_per_number
        block = np.stack([np.asarray(r) for r in raws])
        if block.dtype != np.uint8 or block.ndim != 3 or block.shape[1:] != (
            bpn,
            self.model_length,
        ):
            raise ValueError("expected uint8[K, bytes_per_number, model_len]")
        if self.padded_length != self.model_length:
            block = np.pad(
                block, ((0, 0), (0, 0), (0, self.padded_length - self.model_length))
            )
        # same power-of-two bucketing as the v1 path (ragged coalescer
        # groups must not recompile the unpack mid-round); zero planes
        # decode to zero elements, valid and sliced off below
        k = len(raws)
        bucket = min(1 << max(0, k - 1).bit_length(), MAX_LAZY_BATCH)
        if bucket > k:
            block = np.concatenate(
                [block, np.zeros((bucket - k, *block.shape[1:]), dtype=block.dtype)]
            )
        BYTES_STAGED.labels(layout="wire-planar").inc(block.nbytes)
        staged = jax.device_put(block, self._batch_packed_sharding)
        ok = profiling.timed_kernel(
            "wire_unpack",
            staged.shape[0] * self.padded_length,
            lambda: self._make_planar_ok_fn()(staged),
        )
        ok_host = np.asarray(ok)
        return [staged[i] if ok_host[i] else None for i in range(k)]

    def dispatch_staged_bytes(self, staged):
        """Unpack + validity + fold a staged raw-byte batch WITHOUT syncing
        the acceptance vector: returns the device ``ok`` array still in
        flight. The caller owns the deferred accounting — it must fetch the
        vector eventually and credit ``nb_models`` (what
        ``_ingest_staged_bytes`` does inline, and the streaming pipeline
        does once per drain instead of once per batch)."""
        n_elements = staged.shape[0] * self.padded_length
        if (
            self._fold_fn is not None
            and self.kernel_used == "xla"
            and jax.default_backend() != "cpu"
        ):
            # steady state on accelerators: one fused jit — unpack, validity
            # mask, and fold in a single XLA program, so the intermediate
            # planar tensor (K*L*padded*4 bytes, 8/bpn x the wire bytes)
            # never round-trips HBM. On CPU the two-step path measures ~8%
            # faster (no HBM economics), so fusion stays accelerator-only.
            self.acc, ok = profiling.timed_kernel(
                "wire_ingest",
                n_elements,
                lambda: self._make_ingest_fn()(self.acc, staged),
            )
        else:
            # first call (kernel not yet resolved — auto calibration needs a
            # planar staged batch), a Pallas fold (pallas_call reads its
            # operand from HBM, so fusion would not help), or a CPU backend:
            # two-step path
            planar, ok = profiling.timed_kernel(
                "wire_unpack", n_elements, lambda: self._make_unpack_fn()(staged)
            )
            # dispatch the fold BEFORE syncing the acceptance vector: the
            # fold then overlaps the host-side ok fetch (when kernel
            # profiling is on, the sync points serialize this overlap —
            # XAYNET_KERNEL_PROFILE=0 restores it exactly)
            self.acc = self._fold(self.acc, planar)
        return ok

    def _ingest_staged_bytes(self, staged) -> np.ndarray:
        """Unpack + validity + fold an already device/mesh-resident raw-byte
        batch (``add_wire_batch`` after device_put; the multihost path after
        ``make_array_from_process_local_data``) with an immediate
        acceptance sync."""
        ok_host = np.asarray(self.dispatch_staged_bytes(staged))
        self.nb_models += int(ok_host.sum())
        return ok_host

    # -- kernel selection ---------------------------------------------------

    def _zero_acc(self):
        return jax.device_put(
            jnp.zeros((self.n_limbs, self.padded_length), dtype=jnp.uint32), self._acc_sharding
        )

    def _make_fold_fn(self, kernel: str):
        """The fold callable for ``kernel``, memoized process-wide.

        jit caches by function identity: building a fresh closure per
        aggregator (one per round) would recompile every round and retain
        every old executable.
        """
        if kernel == "native-u64":
            return self._make_native_fold_fn()
        if kernel in ("pallas", "pallas-interpret"):
            interpret = kernel == "pallas-interpret"
            key = (kernel, _mesh_key(self.mesh), self.order)
            fn = _FOLD_FN_CACHE.get(key)
            if fn is None:
                from ..ops import fold_pallas

                order = self.order

                def call(a, s):
                    # late module-attribute lookup so test spies see the call
                    return fold_pallas.fold_planar_batch_pallas(
                        a, s, order, interpret=interpret
                    )

                if self.mesh.devices.size > 1:
                    # the fold is elementwise along the model axis, so each
                    # device runs the Pallas kernel on its local shard —
                    # shard_map makes the kernel multichip without a custom
                    # partitioner; the outer jit restores accumulator donation
                    fn = jax.jit(
                        _shard_map(
                            call,
                            mesh=self.mesh,
                            in_specs=(P(None, MODEL_AXIS), P(None, None, MODEL_AXIS)),
                            out_specs=P(None, MODEL_AXIS),
                        ),
                        donate_argnums=(0,),
                    )
                else:
                    fn = call
                _FOLD_FN_CACHE[key] = fn
            return fn
        key = ("xla", self.order)
        fn = _FOLD_FN_CACHE.get(key)
        if fn is None:
            order = self.order
            fn = _FOLD_FN_CACHE[key] = lambda a, s: fold_planar_batch(a, s, order)
        return fn

    def _make_native_fold_fn(self):
        """Host C++ single-pass u64 fold (``utils.native``), same
        ``(acc, staged) -> acc`` contract as the device folds but over host
        numpy (jax inputs are viewed with ``np.asarray`` — zero-copy for
        CPU-backend arrays; mesh-sharded inputs gather once). NOT memoized
        in ``_FOLD_FN_CACHE``: there is no compiled executable to leak, and
        the closure carries a per-aggregator spare accumulator so the
        steady state allocates nothing (a fresh 200 MB result buffer costs
        ~0.15 s/fold in page faults at 25M params).

        On a multi-device mesh the fold runs ONE CONCURRENT STRIDED KERNEL
        CALL PER SHARD — each folds its device's contiguous plane slice
        straight out of the full staged batch (zero slice copies) under
        the per-shard thread budget (the process-wide auto-calibrated
        budget split across shards, ``XAYNET_NATIVE_SHARD_THREADS`` to
        pin) — so the host kernel honors the mesh decomposition instead of
        refusing it, and the result stays host-resident (``unmask_limbs``
        and ``snapshot`` handle a host accumulator)."""
        order = self.order
        order_limbs = host_limbs.order_limbs_for(order)
        # u64 running-sum headroom: K+1 terms < order each must fit u64
        # (None = pow2-boundary order, which wraps exactly for any K)
        headroom = (
            None if order == (1 << (32 * self.n_limbs)) else (1 << 64) // order
        )
        n_shards = self.mesh.devices.size
        state = {"spare": None, "warned": False, "budget": 0}

        def fold(acc, staged):
            # host kernel reads host memory (zero-copy on CPU)  # lint: sync-ok
            stack_np = np.asarray(staged)  # lint: sync-ok
            if headroom is not None and stack_np.shape[0] + 1 > headroom:
                # the usability check binds kernel_used on the FIRST batch's
                # K; a later larger batch past the u64 headroom (high-order
                # 2-limb configs) must take the XLA fold, not
                # fold_planar_batch_host's silent pairwise-numpy fallback
                if not state["warned"]:
                    state["warned"] = True
                    logger.warning(
                        "native-u64 headroom exceeded at K=%d (order ~2^%d); "
                        "folding oversized batches with the XLA kernel",
                        stack_np.shape[0],
                        order.bit_length(),
                    )
                return fold_planar_batch(np.asarray(acc), stack_np, order)  # lint: sync-ok
            acc_np = np.asarray(acc)  # lint: sync-ok
            if n_shards > 1:
                out = _sharded_native_fold(acc_np, stack_np, order_limbs, n_shards, state)
            else:
                out = host_limbs.fold_planar_batch_host(
                    acc_np, stack_np, order_limbs, out=state["spare"]
                )
            # the old accumulator's buffer becomes the next spare: the
            # aggregator owns ``acc`` exclusively (readers go through
            # snapshot(), which copies), so it is dead once the caller
            # rebinds self.acc to the returned array. jax-owned buffers
            # (the initial zeros) are read-only views — never reused.
            state["spare"] = (
                acc_np if (out is not acc_np and acc_np.flags.writeable) else None
            )
            return out

        return fold

    def packed_staging_usable(self) -> bool:
        """Whether packed byte-planar staging actually shrinks anything:
        the wire width must be narrower than the limb width (at the
        ``order == 2^(32L)`` boundary bpn == 4L and packing is a no-op)."""
        return self.packed_width < 4 * self.n_limbs

    def _make_native_packed_fold_fn(self):
        """Host packed fold ``(acc u32[L,n], packed u8[K,bpn,n]) -> acc``:
        the native kernel reads the byte planes directly (25% less batch
        traffic at bpn=6 than the unpacked planar read), with the same
        spare ping-pong, multi-shard fan-out and oversized-batch fallback
        as :meth:`_make_native_fold_fn`."""
        order = self.order
        order_limbs = host_limbs.order_limbs_for(order)
        n_limbs = self.n_limbs
        headroom = (
            None if order == (1 << (32 * self.n_limbs)) else (1 << 64) // order
        )
        n_shards = self.mesh.devices.size
        state = {"spare": None, "warned": False, "budget": 0}

        def fold(acc, packed):
            packed_np = np.asarray(packed)  # host kernel reads host memory  # lint: sync-ok
            acc_np = np.asarray(acc)  # lint: sync-ok
            if headroom is not None and packed_np.shape[0] + 1 > headroom:
                if not state["warned"]:
                    state["warned"] = True
                    logger.warning(
                        "native-u64 headroom exceeded at K=%d (order ~2^%d); "
                        "folding oversized packed batches with the XLA kernel",
                        packed_np.shape[0],
                        order.bit_length(),
                    )
                planar = host_limbs.unpack_planar(packed_np, n_limbs)
                return fold_planar_batch(acc_np, planar, order)
            if n_shards > 1:
                out = _sharded_native_fold_packed(
                    acc_np, packed_np, order_limbs, n_shards, state
                )
            else:
                out = host_limbs.fold_packed_batch_host(
                    acc_np, packed_np, order_limbs, out=state["spare"]
                )
            state["spare"] = (
                acc_np if (out is not acc_np and acc_np.flags.writeable) else None
            )
            return out

        return fold

    def _make_packed_fold_fn(self, kernel: str):
        """The packed-batch fold callable for ``kernel`` (byte-planar
        ``uint8[K, bpn, padded]`` input), memoized process-wide like the
        planar fold fns. Device kernels fuse the in-graph unpack with the
        fold in one jit (``ops.fold_jax.fold_packed_batch``) so only packed
        bytes cross host->device; Pallas kernels unpack in a separate jit
        (``pallas_call`` reads its operand from HBM — fusion buys nothing)."""
        if kernel == "native-u64":
            return self._make_native_packed_fold_fn()
        n_limbs, order = self.n_limbs, self.order
        if kernel in ("pallas", "pallas-interpret"):
            from ..ops import limbs_jax

            unpack = jax.jit(lambda p: limbs_jax.packed_planar_to_limbs(p, n_limbs))
            base_fold = self._make_fold_fn(kernel)
            return lambda a, p: base_fold(a, unpack(p))
        key = ("xla-packed", _mesh_key(self.mesh), n_limbs, order)
        fn = _FOLD_FN_CACHE.get(key)
        if fn is None:
            if self.mesh.devices.size > 1:

                def call(a, p):
                    return fold_packed_batch(a, p, n_limbs, order)

                fn = jax.jit(
                    _shard_map(
                        call,
                        mesh=self.mesh,
                        in_specs=(P(None, MODEL_AXIS), P(None, None, MODEL_AXIS)),
                        out_specs=P(None, MODEL_AXIS),
                    ),
                    donate_argnums=(0,),
                )
            else:
                fn = lambda a, p: fold_packed_batch(a, p, n_limbs, order)
            _FOLD_FN_CACHE[key] = fn
        return fn

    def _fold_packed(self, acc, staged_packed):
        """Fold a packed byte-planar staged batch (same ``masked_add``
        telemetry op as the planar fold: one /metrics series answers 'how
        fast is the masked add' whichever staging layout fed it). Callers
        resolve ``kernel_used`` first — packed staging never drives the
        auto-calibration (that races on a planar batch)."""
        if self._packed_fold_fn is None:
            if self.kernel_used is None:
                raise RuntimeError("kernel must be resolved before a packed fold")
            self._packed_fold_fn = self._make_packed_fold_fn(self.kernel_used)
        return profiling.timed_kernel(
            "masked_add",
            staged_packed.shape[0] * staged_packed.shape[-1],
            lambda: self._packed_fold_fn(acc, staged_packed),
        )

    def _native_u64_usable(self, k: int) -> bool:
        """Whether the native u64 fold can serve THIS aggregator: an order
        within 2 limbs whose K+1-term running sum fits u64
        (``fold_planar_batch_host``'s fast path — anything else would
        silently fall back to the slow pairwise tree), and a loadable
        shared library. Multi-device meshes are served too: each device's
        contiguous plane slice folds through the strided kernel entry with
        a per-shard thread budget (one concurrent call per shard), so the
        mesh no longer forces the XLA fallback."""
        if self.n_limbs > 2:
            return False
        if self.order != (1 << (32 * self.n_limbs)) and (k + 1) > (
            (1 << 64) // self.order
        ):
            return False
        from ..utils import native

        return native.load() is not None

    def _make_unpack_fn(self):
        """Device wire-unpack + validity callable, memoized process-wide
        (same identity-caching rationale as the fold fns)."""
        bpn = self.config.bytes_per_number
        key = ("unpack", _mesh_key(self.mesh), bpn, self.order)
        fn = _FOLD_FN_CACHE.get(key)
        if fn is not None:
            return fn
        multi = self.mesh.devices.size > 1
        unpack_mask = _build_wire_unpack(bpn, self.order, multi)
        if multi:
            fn = jax.jit(
                _shard_map(
                    unpack_mask,
                    mesh=self.mesh,
                    in_specs=(P(None, MODEL_AXIS),),
                    out_specs=(P(None, None, MODEL_AXIS), P()),
                )
            )
        else:
            fn = jax.jit(unpack_mask)
        _FOLD_FN_CACHE[key] = fn
        return fn

    def _make_planar_ok_fn(self):
        """Device planar (wire-v2) validity callable, memoized process-wide
        (same identity-caching rationale as ``_make_unpack_fn``). Output is
        only ``ok[K]`` — the staged packed bytes themselves are the result."""
        key = ("planar-ok", _mesh_key(self.mesh), self.n_limbs, self.order)
        fn = _FOLD_FN_CACHE.get(key)
        if fn is not None:
            return fn
        multi = self.mesh.devices.size > 1
        check = _build_planar_ok(self.n_limbs, self.order, multi)
        if multi:
            fn = jax.jit(
                _shard_map(
                    check,
                    mesh=self.mesh,
                    in_specs=(P(None, None, MODEL_AXIS),),
                    out_specs=P(),
                )
            )
        else:
            fn = jax.jit(check)
        _FOLD_FN_CACHE[key] = fn
        return fn

    def _make_ingest_fn(self):
        """Fused wire ingest: the shared unpack+validity body composed with
        the XLA fold in ONE jit (donated accumulator), memoized
        process-wide."""
        bpn = self.config.bytes_per_number
        key = ("ingest", _mesh_key(self.mesh), bpn, self.order)
        fn = _FOLD_FN_CACHE.get(key)
        if fn is not None:
            return fn
        multi = self.mesh.devices.size > 1
        unpack_mask = _build_wire_unpack(bpn, self.order, multi)
        order = self.order

        def ingest(acc, raw):
            planar, ok = unpack_mask(raw)
            return fold_planar_batch(acc, planar, order), ok

        if multi:
            fn = jax.jit(
                _shard_map(
                    ingest,
                    mesh=self.mesh,
                    in_specs=(P(None, MODEL_AXIS), P(None, MODEL_AXIS)),
                    out_specs=(P(None, MODEL_AXIS), P()),
                ),
                donate_argnums=(0,),
            )
        else:
            fn = jax.jit(ingest, donate_argnums=(0,))
        _FOLD_FN_CACHE[key] = fn
        return fn

    def _auto_cache_key(self, k: int) -> tuple:
        """Auto-verdict memo key. K is part of it: a verdict timed on a
        small remainder flush must not bind the steady-state batch size
        (and vice versa); the mesh size too — same padded_length on
        different meshes means a different per-device shard (ADVICE r04)."""
        return (
            jax.default_backend(),
            self.mesh.devices.size,
            self.n_limbs,
            self.padded_length,
            self.order,
            k,
        )

    def _resolve_kernel_cheap(self, k: int) -> None:
        """Resolve ``kernel_used`` when no timing run is needed — explicit
        kernel, or an auto verdict already memoized for this shape. Callers
        invoke this BEFORE staging the first batch: when the winner is the
        host-native kernel, skipping resolution-time ``device_put`` saves a
        full-batch host->device copy per round (~13 GB at 25M/batch 64)
        whose result the native fold would only view back on the host."""
        if self.kernel_used is not None:
            return
        if self.kernel != "auto":
            used = self.kernel
            if used == "native-u64" and not self._native_u64_usable(k):
                logger.warning(
                    "native-u64 fold unavailable (no loadable library, or order "
                    "outside the u64 fast path); falling back to xla"
                )
                used = "xla"
            self.kernel_used = used
            return
        key = self._auto_cache_key(k)
        cached = _AUTO_KERNEL_CACHE.get(key)
        if cached is not None:
            self.kernel_used = cached
            logger.info("aggregation kernel resolved: %s (auto, cached verdict)", cached)
            return
        # disk tier (utils.calibcache): a verdict a PREVIOUS process raced
        # under the same environment fingerprint — the fresh process's
        # first round skips the probe race entirely
        from ..utils import calibcache

        warm = calibcache.get("fold", key)
        if warm is not None:
            _AUTO_KERNEL_CACHE[key] = warm
            self.kernel_used = warm
            logger.info("aggregation kernel resolved: %s (auto, persisted verdict)", warm)

    def _fold(self, acc, staged):
        if self._fold_fn is None:
            self._resolve_kernel(staged)  # may already set _fold_fn (winner)
            if self._fold_fn is None:
                self._fold_fn = self._make_fold_fn(self.kernel_used)
        # device-synced timing of the masked modular add (the hot path);
        # staged is planar [K, L, padded_len] -> K x padded group elements
        return profiling.timed_kernel(
            "masked_add",
            staged.shape[0] * staged.shape[-1],
            lambda: self._fold_fn(acc, staged),
        )

    def _resolve_kernel(self, staged) -> None:
        """Fix ``kernel_used`` for the aggregator's lifetime.

        ``auto`` calibrates both kernels against the first real staged batch
        (fresh zero accumulators — the folds donate their accumulator), takes
        the faster steady-state time, and falls back to XLA if the Pallas
        (Mosaic) compile fails so a broken kernel can never sink a round.
        Verdicts are memoized process-wide: a coordinator builds a fresh
        aggregator every round, but the answer only depends on the backend
        and the problem shape.
        """
        self._resolve_kernel_cheap(staged.shape[0])
        if self.kernel_used is not None:
            return
        backend = jax.default_backend()
        key = self._auto_cache_key(staged.shape[0])
        if backend == "cpu":
            # interpret-mode Pallas is an oracle, not a production kernel —
            # but the native single-pass u64 fold IS: race it against XLA on
            # the real staged batch (it wins ~2.5x at the 25M bench shape;
            # BENCH_r05 showed auto leaving that on the table by
            # short-circuiting to XLA here)
            candidates = ["xla"]
            if self._native_u64_usable(staged.shape[0]):
                candidates.append("native-u64")
        else:
            candidates = ["xla", "pallas"]
        if len(candidates) == 1:
            self.kernel_used = candidates[0]
        else:
            timings, fns = {}, {}
            # one scratch accumulator shared across candidates and calls: the
            # folds donate their input and return the new buffer, so chaining
            # the return keeps the transient footprint at one extra
            # accumulator instead of two fresh zeros per candidate while
            # self.acc and the batch are live (ADVICE r04). XLA runs first;
            # if the Pallas leg dies mid-run its possibly-donated scratch is
            # never reused (no candidates follow it). Steady-state times go
            # through the telemetry registry
            # (xaynet_kernel_calibration_seconds{kernel=...}).
            scratch = self._zero_acc()
            host_staged = None
            for name in candidates:
                try:
                    fold = self._make_fold_fn(name)
                    arg = staged
                    if name == "native-u64":
                        # the production native path never stages to device
                        # (the kernel reads host memory), so time it on the
                        # host view — on the CPU backend this is zero-copy
                        if host_staged is None:
                            host_staged = np.asarray(staged)  # calibration host view  # lint: sync-ok
                        arg = host_staged
                    scratch = fold(scratch, arg)
                    scratch = jax.block_until_ready(scratch)  # compile / first touch  # lint: sync-ok
                    scratch, dt = profiling.measure(lambda: fold(scratch, arg))
                    timings[name] = dt
                    profiling.record_calibration(name, dt)
                    fns[name] = fold
                except Exception as e:  # Mosaic compile/run failure -> keep XLA
                    logger.warning(
                        "aggregation kernel %s unavailable: %s: %s", name, type(e).__name__, e
                    )
            self.kernel_used = min(timings, key=timings.get) if timings else "xla"
            # keep the winner's already-compiled callable
            self._fold_fn = fns.get(self.kernel_used)
            logger.info("aggregation kernel auto-calibration: %s -> %s", timings, self.kernel_used)
        _AUTO_KERNEL_CACHE[key] = self.kernel_used
        from ..utils import calibcache

        calibcache.put("fold", key, self.kernel_used)
        logger.info(
            "aggregation kernel resolved: %s (auto on %s backend)", self.kernel_used, backend
        )

    def mask_planar(self, mask_vect) -> np.ndarray:
        """Normalize an aggregated mask (wire or planar) to the padded
        planar layout every unmask path subtracts in — shared by
        :meth:`unmask_limbs` and the eager per-shard unmask staging
        (docs/DESIGN.md §22), which needs the planar before the drain."""
        mask = np.asarray(mask_vect, dtype=np.uint32)
        planar = wire_to_planar(mask) if mask.shape == (self.model_length, self.n_limbs) else mask
        if planar.shape[1] != self.padded_length:
            planar = np.pad(planar, ((0, 0), (0, self.padded_length - planar.shape[1])))
        return planar

    def unmask_limbs(self, mask_vect) -> np.ndarray:
        """Subtract the aggregated mask; returns host wire ``uint32[model_len, L]``."""
        planar = self.mask_planar(mask_vect)
        if self._live_plan is not None:
            # reduce-scatter unmask: each shard subtracts ITS slice of the
            # mask against its own accumulator buffer — the aggregate is
            # never reassembled before subtraction, and the only gather is
            # the unmasked result crossing to the host for decode (the
            # final model download)
            return profiling.timed_kernel(
                "unmask",
                self.padded_length,
                lambda: self._unmask_plan(self._live_plan, planar),
            )
        if not isinstance(self.acc, jax.Array):
            # the native fold keeps the accumulator host-resident (it would
            # previously ride into the jit as an implicit upload; a
            # multi-device mesh makes that upload a sharding conflict):
            # unmask is the same elementwise modular subtract, on host
            # limbs, for a result the caller reads on the host anyway
            acc_wire = np.ascontiguousarray(
                np.asarray(self.acc)[:, : self.model_length].T
            )
            mask_wire = np.ascontiguousarray(planar[:, : self.model_length].T)
            order_limbs = host_limbs.order_limbs_for(self.order)
            return profiling.timed_kernel(
                "unmask",
                self.padded_length,
                lambda: np.ascontiguousarray(
                    host_limbs.mod_sub(acc_wire, mask_wire, order_limbs)
                ),
            )
        mask_dev = jax.device_put(jnp.asarray(planar), self._acc_sharding)
        out = profiling.timed_kernel(
            "unmask",
            self.padded_length,
            lambda: _unmask_kernel(self.acc, mask_dev, self.order),
        )
        return np.ascontiguousarray(np.asarray(out)[:, : self.model_length].T)

    def unmask_shard(self, plan, d: int, mask_planar: np.ndarray, out: np.ndarray) -> None:
        """One shard's leg of the reduce-scatter unmask: subtract shard
        ``d``'s slice of the aggregated mask against its own accumulator
        buffer and write the unmasked wire slice into ``out``. Shared by
        the drain-time ``_unmask_plan`` pass and the eager per-shard
        unmask tail jobs (docs/DESIGN.md §22), which run it concurrently
        from the shard workers — distinct ``out`` row ranges per shard,
        no synchronization needed."""
        lo, hi = plan.slices[d]
        real_hi = min(hi, self.model_length)
        if lo >= real_hi:
            return
        if plan.native:
            order_limbs = host_limbs.order_limbs_for(self.order)
            acc_w = np.ascontiguousarray(plan.accs[d][:, : real_hi - lo].T)  # lint: guarded-ok: drain barrier read
            mask_w = np.ascontiguousarray(mask_planar[:, lo:real_hi].T)
            out[lo:real_hi] = host_limbs.mod_sub(acc_w, mask_w, order_limbs)
            return
        mask_dev = jax.device_put(
            np.ascontiguousarray(mask_planar[:, lo:hi]), plan.devices[d]
        )
        res = _unmask_kernel(plan.accs[d], mask_dev, self.order)  # lint: guarded-ok: drain barrier read
        # deliberate barrier: the unmasked slice is this shard's FINAL device
        # read of the round — the eager tail job (or the drain pass) fetches
        # it here so Unmask never touches the device again  # lint: sync-ok
        out[lo:real_hi] = np.asarray(res)[:, : real_hi - lo].T  # lint: sync-ok

    def _unmask_plan(self, plan, mask_planar: np.ndarray) -> np.ndarray:
        """Per-shard in-place unmask against a live reduce-scatter plan:
        native plans subtract on each host shard buffer, device plans
        dispatch one subtract per device (all in flight before the first
        fetch) — either way only the UNMASKED per-shard slices move, once,
        into the host wire result."""
        out = np.empty((self.model_length, self.n_limbs), dtype=np.uint32)
        if plan.native:
            for d in range(len(plan.slices)):
                self.unmask_shard(plan, d, mask_planar, out)
        else:
            pending = []
            for d, (lo, hi) in enumerate(plan.slices):
                mask_dev = jax.device_put(
                    np.ascontiguousarray(mask_planar[:, lo:hi]), plan.devices[d]
                )
                # dispatch every shard's subtract before fetching any: the
                # per-device kernels overlap, the downloads serialize after
                pending.append(
                    (lo, hi, _unmask_kernel(plan.accs[d], mask_dev, self.order))  # lint: guarded-ok: drain barrier read
                )
            for lo, hi, res in pending:
                real_hi = min(hi, self.model_length)
                if lo < real_hi:
                    out[lo:real_hi] = np.asarray(res)[:, : real_hi - lo].T
        BYTES_REDUCED.labels(path="gather").inc(out.nbytes)
        return np.ascontiguousarray(out)

    def snapshot(self) -> np.ndarray:
        """Host wire-layout copy of the aggregate (checkpoints / tests)."""
        return np.ascontiguousarray(np.asarray(self.acc)[:, : self.model_length].T)

    def restore(self, wire: np.ndarray, nb_models: int) -> None:
        """Restore from a host wire-layout snapshot."""
        planar = self._to_planar_padded(wire[None, :, :])[0]
        self.acc = jax.device_put(jnp.asarray(planar), self._acc_sharding)
        self.nb_models = nb_models

    def snapshot_shards(self) -> Optional[list[tuple[int, int, np.ndarray]]]:
        """Packed per-shard planes ``[(lo, hi, uint32[L, hi-lo])]`` of the
        PADDED model axis — the journal form that lets a device round
        checkpoint without reassembling the global accumulator (each plane
        is one device/shard slice, fetched independently). Returns None when
        no per-shard decomposition exists; the caller falls back to the
        gathered wire snapshot."""
        plan = self._live_plan
        if plan is not None:
            return [
                (lo, hi, np.asarray(acc))  # lint: guarded-ok: drain barrier read
                for (lo, hi), acc in zip(plan.slices, plan.accs)
            ]
        acc = self._acc
        if not isinstance(acc, jax.Array):
            return None
        planes: dict[int, tuple[int, int, np.ndarray]] = {}
        for s in acc.addressable_shards:
            col = s.index[1]
            lo = col.start if col.start is not None else 0
            hi = col.stop if col.stop is not None else self.padded_length
            if lo not in planes:  # replicated shardings repeat slices
                planes[lo] = (lo, hi, np.asarray(s.data))
        return [planes[lo] for lo in sorted(planes)]

    def restore_shards(self, planes: list[tuple[int, int, np.ndarray]], nb_models: int) -> None:
        """Restore the planar accumulator from journal planes, shard-exact
        when the current mesh decomposition matches the journaled one (one
        ``device_put`` per plane, no host-side global assembly), host-side
        concat + scatter otherwise (mesh shape changed across the restart)."""
        shape = (self.n_limbs, self.padded_length)
        target = None
        try:
            index_map = self._acc_sharding.addressable_devices_indices_map(shape)
            by_lo = {lo: np.ascontiguousarray(p, dtype=np.uint32) for lo, _hi, p in planes}
            arrays = []
            for dev, idx in index_map.items():
                col = idx[1]
                lo = col.start if col.start is not None else 0
                hi = col.stop if col.stop is not None else self.padded_length
                plane = by_lo[lo]  # KeyError -> decomposition mismatch -> fallback
                if plane.shape != (self.n_limbs, hi - lo):
                    raise ValueError(f"plane [{lo},{hi}) shape {plane.shape}")
                arrays.append(jax.device_put(plane, dev))
            target = jax.make_array_from_single_device_arrays(
                shape, self._acc_sharding, arrays
            )
        except (KeyError, ValueError, TypeError) as exc:
            logger.info("shard-exact restore unavailable (%s); reassembling on host", exc)
        if target is None:
            planar = np.zeros(shape, dtype=np.uint32)
            for lo, hi, plane in planes:
                planar[:, lo:hi] = plane
            target = jax.device_put(jnp.asarray(planar), self._acc_sharding)
        self.acc = target  # setter drops any stale plan; streaming re-leases
        self.nb_models = nb_models

    def reset(self) -> None:
        self.acc = jax.device_put(
            jnp.zeros((self.n_limbs, self.padded_length), dtype=jnp.uint32), self._acc_sharding
        )
        self.nb_models = 0
