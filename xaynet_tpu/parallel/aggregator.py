"""Sharded device-resident aggregation of masked updates.

The coordinator-side hot path (reference analogue:
rust/xaynet-server/src/state_machine/phases/update.rs:119-152, which does one
sequential big-int pass per accepted update). Here the running aggregate is
an HBM-resident **planar** ``uint32[L, padded_len]`` buffer sharded over the
model-length axis of a device mesh; incoming masked updates are staged into
``[K, L, padded_len]`` batches and folded in with the single-pass lazy-carry
kernel (``ops.fold_jax``) — one full read of the batch plus a handful of
tiny passes, no collectives (the length axis is embarrassingly parallel).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.mask.config import MaskConfig
from ..ops import limbs as host_limbs
from ..ops.fold_jax import MAX_LAZY_BATCH, fold_planar_batch, p_mod_sub, wire_to_planar
from .mesh import MODEL_AXIS, make_mesh, pad_to_multiple

_unmask_kernel = jax.jit(p_mod_sub, static_argnames=("order",))


class ShardedAggregator:
    """Accumulates masked updates on-device, sharded over the model axis."""

    def __init__(self, config: MaskConfig, model_length: int, mesh=None, use_pallas: bool = False):
        self.use_pallas = use_pallas
        self.config = config
        self.model_length = model_length
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        self.padded_length = pad_to_multiple(model_length, n_dev)
        self.n_limbs = host_limbs.n_limbs_for_order(config.order)
        self.order = config.order
        # planar shardings: model axis is the innermost (lane) dimension
        self._acc_sharding = NamedSharding(self.mesh, P(None, MODEL_AXIS))
        self._batch_sharding = NamedSharding(self.mesh, P(None, None, MODEL_AXIS))
        self.acc = jax.device_put(
            jnp.zeros((self.n_limbs, self.padded_length), dtype=jnp.uint32), self._acc_sharding
        )
        self.nb_models = 0

    def _to_planar_padded(self, stack: np.ndarray) -> np.ndarray:
        """Wire ``[K, n, L]`` -> planar padded ``[K, L, padded_len]`` (host)."""
        planar = wire_to_planar(stack)
        if self.padded_length != planar.shape[2]:
            planar = np.pad(planar, ((0, 0), (0, 0), (0, self.padded_length - planar.shape[2])))
        return planar

    def add_batch(self, stack) -> None:
        """Fold wire-layout ``uint32[K, model_len, L]`` updates into the aggregate.

        Zero padding columns are valid group elements, so padding never
        affects the real slice.
        """
        stack = np.asarray(stack, dtype=np.uint32)
        if stack.ndim != 3 or stack.shape[2] != self.n_limbs:
            raise ValueError("expected uint32[K, model_len, L]")
        if stack.shape[1] != self.model_length:
            raise ValueError("model length mismatch")
        if stack.shape[0] > MAX_LAZY_BATCH:
            raise ValueError("batch too large for lazy-carry fold")
        staged = jax.device_put(self._to_planar_padded(stack), self._batch_sharding)
        if self.use_pallas:
            from ..ops.fold_pallas import fold_planar_batch_pallas

            self.acc = fold_planar_batch_pallas(self.acc, staged, self.order)
        else:
            self.acc = fold_planar_batch(self.acc, staged, self.order)
        self.nb_models += stack.shape[0]

    def add_planar_batch(self, stack_planar: jax.Array) -> None:
        """Fold an already device-resident planar ``[K, L, padded_len]`` batch."""
        if self.use_pallas:
            from ..ops.fold_pallas import fold_planar_batch_pallas

            self.acc = fold_planar_batch_pallas(self.acc, stack_planar, self.order)
        else:
            self.acc = fold_planar_batch(self.acc, stack_planar, self.order)
        self.nb_models += stack_planar.shape[0]

    def unmask_limbs(self, mask_vect) -> np.ndarray:
        """Subtract the aggregated mask; returns host wire ``uint32[model_len, L]``."""
        mask = np.asarray(mask_vect, dtype=np.uint32)
        planar = wire_to_planar(mask) if mask.shape == (self.model_length, self.n_limbs) else mask
        if planar.shape[1] != self.padded_length:
            planar = np.pad(planar, ((0, 0), (0, self.padded_length - planar.shape[1])))
        mask_dev = jax.device_put(jnp.asarray(planar), self._acc_sharding)
        out = _unmask_kernel(self.acc, mask_dev, self.order)
        return np.ascontiguousarray(np.asarray(out)[:, : self.model_length].T)

    def snapshot(self) -> np.ndarray:
        """Host wire-layout copy of the aggregate (checkpoints / tests)."""
        return np.ascontiguousarray(np.asarray(self.acc)[:, : self.model_length].T)

    def restore(self, wire: np.ndarray, nb_models: int) -> None:
        """Restore from a host wire-layout snapshot."""
        planar = self._to_planar_padded(wire[None, :, :])[0]
        self.acc = jax.device_put(jnp.asarray(planar), self._acc_sharding)
        self.nb_models = nb_models

    def reset(self) -> None:
        self.acc = jax.device_put(
            jnp.zeros((self.n_limbs, self.padded_length), dtype=jnp.uint32), self._acc_sharding
        )
        self.nb_models = 0
