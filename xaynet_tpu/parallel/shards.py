"""Per-shard decomposition of the aggregation accumulator.

The mesh-sharded fold (``ShardedAggregator``) runs ONE program over the
whole mesh per batch: a single dispatch, a single accumulator, a single
host sync at drain. That shape cannot overlap per-device work — every
device waits for the slowest transfer, and the host-native kernel was
locked out of multi-device meshes entirely because it had no notion of a
device slice.

A :class:`ShardPlan` decomposes the aggregator's planar accumulator into
per-shard owned buffers — one per mesh device, each covering that device's
contiguous model-axis column slice (``mesh.shard_slices``) — so the
streaming pipeline can run ONE FOLD WORKER PER SHARD with independent
queues, donated per-shard accumulators, and per-shard host→device
transfers that overlap other shards' in-flight folds (the DrJAX-style
MapReduce pipelining of arxiv 2403.07128, applied across the mesh instead
of across batches).

Two shard-fold backends, chosen by the aggregator's resolved kernel:

- **native-u64** — per-shard host buffers folded by the threaded C++
  kernel. The strided entry (``ops.limbs.fold_planar_slice_host``) reads a
  shard's column slice straight out of the full staged batch, so the
  sequential multi-device fold and the bench's fold-only loop copy
  nothing; the streaming path folds contiguous per-shard ring buffers.
  Each call carries a per-shard thread budget: the process-wide
  auto-calibrated budget (``XAYNET_NATIVE_THREADS`` / 2x cores) split
  across the shards that now run concurrently, overridable with
  ``XAYNET_NATIVE_SHARD_THREADS``.
- **device kernels** (xla/pallas) — per-device single-device arrays folded
  by the already-jitted ``fold_planar_batch`` (its ``donate_argnums=(0,)``
  is the per-shard accumulator donation); the executable is shared across
  shards (same shapes, same program).

Exactness: the fold is an exact modular sum and the model axis is
embarrassingly parallel, so any decomposition of the column axis folds to
the byte-identical aggregate — per-shard progress skew (shard A two
batches ahead of shard B) changes nothing once every shard has folded
every batch, which is what the streaming pipeline's per-batch commit
barrier guarantees.

Ownership contract: while a plan is ACTIVE (built and not yet
reassembled), the per-shard buffers are the authoritative accumulator and
the aggregator's global ``acc`` is stale — for device kernels the first
donated fold actually invalidates it (the zero-copy decomposition aliases
its buffers). ``reassemble()`` publishes the per-shard state back as the
global accumulator; the streaming pipeline calls it from ``drain()``, its
cross-shard barrier.
"""

from __future__ import annotations

import logging
import os
import threading
import weakref
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..ops import limbs as host_limbs
from .mesh import shard_slices

logger = logging.getLogger(__name__)

SHARD_THREADS_ENV = "XAYNET_NATIVE_SHARD_THREADS"


def _release_plan_leases(pool, leases: list) -> None:
    """Module-level so a plan's GC finalizer holds no plan reference."""
    for lease in leases:
        pool.release(lease)


def shard_thread_budget(n_shards: int, explicit: int = 0) -> int:
    """Per-shard native worker-thread budget: an explicit setting wins,
    then the ``XAYNET_NATIVE_SHARD_THREADS`` env pin (what the bench
    records next to its headline), then the process-wide auto-calibrated
    budget split across the shards that will run concurrently."""
    if explicit > 0:
        return explicit
    env = os.environ.get(SHARD_THREADS_ENV, "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            logger.warning("ignoring non-integer %s=%r", SHARD_THREADS_ENV, env)
    return max(1, host_limbs.native_fold_threads() // n_shards)


class ShardPlan:
    """Per-shard accumulator state + fold entry points for one aggregator.

    Built against a resolved kernel (``agg.kernel_used``); ``zero_accs``
    starts from zeros without reading ``agg.acc`` (kernel calibration and
    tests race plans without touching the live accumulator).
    """

    def __init__(self, agg, shard_threads: int = 0, zero_accs: bool = False,
                 pool=None, tenant: str = "default"):
        if agg.kernel_used is None:
            raise ValueError("kernel must be resolved before building a shard plan")
        self.agg = agg
        # paged-pool seam (docs/DESIGN.md §19): with a pool, the per-shard
        # accumulator/spare buffers are page runs LEASED from the shared
        # arena under this plan's tenant instead of privately-owned
        # allocations — tenants' variable-length plans pack into one slab
        # set. Device plans lease from the capacity LEDGER (fold kernels
        # donate buffers, so page identity cannot survive a fold there).
        # Pages release at the round's unmask (`release_pages`), with a GC
        # finalizer + the Idle-phase reclaim as crash-path backstops.
        self.tenant = tenant
        self._pool = pool
        self._pool_leases: list = []
        self.native = agg.kernel_used == "native-u64"
        self.n_shards = agg.mesh.devices.size
        self.slices = shard_slices(agg.padded_length, self.n_shards)
        self.devices = list(agg.mesh.devices.flat)
        self.order_limbs = host_limbs.order_limbs_for(agg.order)
        self.n_threads = shard_thread_budget(self.n_shards, shard_threads) if self.native else 0
        self._pool: ThreadPoolExecutor | None = None
        self._warned_fallback = False  # guarded-by: _device_dispatch_lock
        # serializes device folds issued from the D worker threads: jax's
        # dispatch/execution path is not reliably thread-safe for
        # concurrent donating jit calls on the virtual-device CPU backend
        # (~1 in 40k folds lands a torn shard slice under scheduler
        # contention — reproduced with no fault injection). On CPU the
        # lock is held through COMPLETION: the virtual devices share the
        # physical cores, so serialized folds lose no real parallelism
        # (XLA's intra-op pool still spans the cores, and staging copies
        # keep overlapping). On real accelerators only the host-side
        # dispatch serializes — per-device execution stays concurrent,
        # which is the point of the shard fan-out. The native path never
        # takes the lock (synchronous GIL-released kernel calls over
        # disjoint buffers).
        self._device_dispatch_lock = threading.Lock()
        self._serialize_device_folds = False
        if not self.native:
            import jax

            self._serialize_device_folds = jax.default_backend() == "cpu"
        # accs/spares carry a guarded-by annotation for the DEVICE fold
        # path (the PR-7 torn-slice class: concurrent donating jit calls);
        # the native path's slot accesses are per-shard-disjoint by
        # construction and carry per-line `# lint: guarded-ok` rationales
        if self.native:
            if zero_accs:
                self.accs = [  # guarded-by: _device_dispatch_lock
                    self._alloc((agg.n_limbs, hi - lo))
                    for lo, hi in self.slices
                ]
            else:
                acc_np = np.asarray(agg.acc)
                self.accs = []
                for lo, hi in self.slices:
                    buf = self._alloc((agg.n_limbs, hi - lo))
                    np.copyto(buf, acc_np[:, lo:hi])
                    self.accs.append(buf)
                from .aggregator import BYTES_REDUCED

                # host memory has no sharded view: decomposing the global
                # accumulator copies it once (the reduce-scatter layout
                # keeps the plan across drain windows, so this is per
                # round, not per drain)
                BYTES_REDUCED.labels(path="scatter").inc(int(acc_np.nbytes))
            self.spares: list = [  # guarded-by: _device_dispatch_lock
                self._alloc(a.shape) for a in self.accs
            ]
        else:
            import jax
            import jax.numpy as jnp

            if zero_accs:
                self.accs = [
                    jax.device_put(
                        jnp.zeros((agg.n_limbs, hi - lo), dtype=jnp.uint32), dev
                    )
                    for (lo, hi), dev in zip(self.slices, self.devices)
                ]
            elif not isinstance(agg.acc, jax.Array):
                # a host-resident accumulator (an earlier native fold left
                # it on the host): upload each device its slice
                acc_np = np.asarray(agg.acc)
                self.accs = [
                    jax.device_put(np.ascontiguousarray(acc_np[:, lo:hi]), dev)
                    for (lo, hi), dev in zip(self.slices, self.devices)
                ]
            else:
                # zero-copy decomposition: the addressable shards of the
                # mesh-sharded accumulator ARE the per-device slices; the
                # first donated fold invalidates the global array, which is
                # exactly the ownership handoff documented above
                by_start = {
                    s.index[-1].start or 0: s.data for s in agg.acc.addressable_shards
                }
                self.accs = [by_start[lo] for lo, _ in self.slices]
            self.spares = []
            if self._pool is not None:
                # device plans lease from the CAPACITY LEDGER: the
                # accumulator's HBM footprint is charged to the tenant so
                # a plan that would not fit fails fast at build time
                self._pool_leases.append(
                    self._pool.lease_device(
                        self.tenant, agg.n_limbs * agg.padded_length * 4
                    )
                )
        if self._pool is not None:
            # crash-path backstop: a plan dropped without release_pages()
            # gives its pages back at collection time (by then nothing can
            # alias the leased runs); Idle's reclaim covers the rest
            weakref.finalize(
                self, _release_plan_leases, self._pool, self._pool_leases
            )

    def _alloc(self, shape) -> np.ndarray:
        """A zeroed uint32 host buffer: a page-run lease from the shared
        pool when one is attached, a private allocation otherwise."""
        if self._pool is None:
            return np.zeros(shape, dtype=np.uint32)
        lease = self._pool.lease_host(self.tenant, shape, np.uint32)
        self._pool_leases.append(lease)
        return lease.array

    def release_pages(self) -> None:
        """Release every page lease this plan holds (the round's unmask
        path; idempotent against the GC finalizer and the Idle reclaim).
        The per-shard buffers must no longer be read past this point —
        the pool may re-lease their pages to another tenant."""
        if self._pool is None:
            return
        for lease in self._pool_leases:
            self._pool.release(lease)

    # -- folds ------------------------------------------------------------

    def fold_shard(self, d: int, batch) -> None:
        """Fold a per-shard batch ``[K, L, width]`` into shard ``d``'s
        accumulator. Native: a host-contiguous array folded by the C++
        kernel under this plan's per-shard thread budget, ping-ponging the
        shard's donated spare buffer. Device: a ``device[d]``-resident
        array folded by the jitted kernel (accumulator donated).

        The accumulator is reassigned only after the fold call returns, so
        an exception leaves the shard consistent — the streaming pipeline's
        per-shard sync-retry relies on this."""
        if self.native:
            stack_np = np.asarray(batch)  # host-kernel view  # lint: sync-ok
            if not host_limbs.u64_fold_applicable(
                stack_np.shape[0], self.agg.n_limbs, self.order_limbs
            ):
                self._warn_fallback(stack_np.shape[0])
            # native slot accesses: shard d's buffers are owned by its
            # single worker; slots are disjoint across shards and the
            # host kernel performs no device dispatch
            acc = self.accs[d]  # lint: guarded-ok: single-owner shard slot
            out = host_limbs.fold_planar_batch_host(
                acc,
                stack_np,
                self.order_limbs,
                out=self.spares[d],  # lint: guarded-ok: single-owner shard slot
                n_threads=self.n_threads,
            )
            spare_back = acc if (out is not acc and acc.flags.writeable) else None
            self.spares[d] = spare_back  # lint: guarded-ok: single-owner shard slot
            self.accs[d] = out  # lint: guarded-ok: single-owner shard slot
        elif self.agg.kernel_used in ("pallas", "pallas-interpret"):
            from ..ops import fold_pallas

            # late module-attribute lookup so test spies see the call, same
            # as the aggregator's fold builder; the kernel is elementwise
            # along the model axis, so each shard runs it on its own slice
            def call(acc):
                return fold_pallas.fold_planar_batch_pallas(
                    acc,
                    batch,
                    self.agg.order,
                    interpret=self.agg.kernel_used == "pallas-interpret",
                )

            self._locked_device_fold(d, call)
        else:
            from ..ops.fold_jax import fold_planar_batch

            self._locked_device_fold(
                d, lambda acc: fold_planar_batch(acc, batch, self.agg.order)
            )

    def fold_shard_packed(self, d: int, packed) -> None:
        """Fold a per-shard PACKED byte-planar batch ``uint8[K, bpn, width]``
        into shard ``d``'s accumulator (the packed-staging streaming path).
        Native: the strided packed kernel reads the byte planes directly
        (``ops.limbs.fold_packed_batch_host``), falling back to one unpack +
        the planar fold when the u64 path doesn't apply. Device: the fused
        unpack+fold jit (``ops.fold_jax.fold_packed_batch``) on the shard's
        device — only packed bytes ever cross host->device.
        Consistency contract matches :meth:`fold_shard` exactly (the
        accumulator is reassigned only after the fold returns)."""
        if self.native:
            packed_np = np.asarray(packed)  # host-kernel view  # lint: sync-ok
            if not (
                packed_np.shape[1] <= 8
                and host_limbs.u64_fold_applicable(
                    packed_np.shape[0], self.agg.n_limbs, self.order_limbs
                )
            ):
                self._warn_fallback(packed_np.shape[0])
            acc = self.accs[d]  # lint: guarded-ok: single-owner shard slot
            out = host_limbs.fold_packed_batch_host(
                acc,
                packed_np,
                self.order_limbs,
                out=self.spares[d],  # lint: guarded-ok: single-owner shard slot
                n_threads=self.n_threads,
            )
            spare_back = acc if (out is not acc and acc.flags.writeable) else None
            self.spares[d] = spare_back  # lint: guarded-ok: single-owner shard slot
            self.accs[d] = out  # lint: guarded-ok: single-owner shard slot
            return
        from ..ops.fold_jax import fold_packed_batch

        n_limbs, order = self.agg.n_limbs, self.agg.order
        if self.agg.kernel_used in ("pallas", "pallas-interpret"):
            from ..ops import fold_pallas, limbs_jax

            interpret = self.agg.kernel_used == "pallas-interpret"

            def call(acc):
                # the module-level jitted unpack: one shared trace cache
                # across calls/shards instead of a fresh retrace per batch
                planar = limbs_jax.packed_planar_to_limbs_jit(packed, n_limbs)
                return fold_pallas.fold_planar_batch_pallas(
                    acc, planar, order, interpret=interpret
                )

            self._locked_device_fold(d, call)
            return
        self._locked_device_fold(
            d, lambda acc: fold_packed_batch(acc, packed, n_limbs, order)
        )

    def _locked_device_fold(self, d: int, call) -> None:
        """Run one shard's device fold under the dispatch lock; on the CPU
        backend hold it through completion (see the lock's construction
        note). The shard accumulator is reassigned only after ``call``
        returns — an exception leaves the shard consistent."""
        with self._device_dispatch_lock:
            new_acc = call(self.accs[d])
            if self._serialize_device_folds:
                import jax

                new_acc = jax.block_until_ready(new_acc)  # lint: sync-ok
            # reassign INSIDE the lock: the slot write itself must not
            # interleave with another shard's donating dispatch (the PR-7
            # torn-slice hazard this lock exists for)
            self.accs[d] = new_acc

    def fold_shard_slice(self, d: int, full_planar: np.ndarray) -> None:
        """Fold shard ``d``'s column slice straight out of a FULL staged
        planar ``uint32[K, L, padded]`` batch — the strided native read,
        zero slice copies (native plans only)."""
        if not self.native:
            raise RuntimeError("slice folds are a native-kernel path")
        lo, hi = self.slices[d]
        acc, spare = self.accs[d], self.spares[d]  # lint: guarded-ok: single-owner shard slot
        if spare is None:
            spare = np.empty_like(acc)
        if host_limbs.fold_planar_slice_host(
            acc,
            full_planar,
            spare,
            lo,
            hi,
            self.order_limbs,
            n_threads=self.n_threads,
            acc_cols=hi - lo,
        ):
            self.accs[d], self.spares[d] = spare, acc  # lint: guarded-ok: single-owner shard slot
            return
        # u64 headroom exceeded (or library gone mid-round): copy the slice
        # and take the generic fold — exact, just not single-pass
        self._warn_fallback(full_planar.shape[0])
        self.fold_shard(d, np.ascontiguousarray(full_planar[:, :, lo:hi]))

    def fold_full(self, full_planar: np.ndarray) -> None:
        """Fold every shard's slice of a full staged batch CONCURRENTLY
        (one strided kernel call per shard, each under the per-shard thread
        budget) — the sequential multi-device native fold and the bench's
        fold-only loop. The calls release the GIL inside the C++ kernel,
        so a thread pool genuinely overlaps them."""
        if not self.native:
            raise RuntimeError("fold_full is a native-kernel path")
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.n_shards, thread_name_prefix="xn-shard-fold"
            )
        list(
            self._pool.map(
                lambda d: self.fold_shard_slice(d, full_planar), range(self.n_shards)
            )
        )

    def _warn_fallback(self, k: int) -> None:
        if not self._warned_fallback:  # lint: guarded-ok: benign idempotent warn latch
            self._warned_fallback = True  # lint: guarded-ok: benign idempotent warn latch
            logger.warning(
                "native u64 headroom exceeded at K=%d (order ~2^%d); shard "
                "folds taking the generic host path for oversized batches",
                k,
                self.agg.order.bit_length(),
            )

    # -- barrier / reassembly ---------------------------------------------

    def block_until_ready(self) -> None:
        """Wait for every shard's in-flight device fold (native folds are
        synchronous — nothing to wait for)."""
        if not self.native:
            import jax

            # lint: guarded-ok: drain barrier — workers quiesced behind the queue join
            jax.block_until_ready(self.accs)  # lint: sync-ok  # lint: guarded-ok: drain barrier read

    def reassemble(self):
        """The global planar accumulator assembled from the per-shard
        state: zero-copy for device plans
        (``make_array_from_single_device_arrays`` over the per-device
        buffers, which ARE the mesh sharding's shards), one counted
        concatenation copy for native plans (host memory has no sharded
        view). Reduce-scatter contract (DESIGN §17): this is a READ — an
        adopted plan stays authoritative afterwards and keeps folding into
        the same per-shard buffers (``ShardedAggregator.acc`` calls this
        on demand for snapshot/checkpoint/final download). Only an
        explicit ``acc`` WRITE supersedes the plan."""
        if self.native:
            from .aggregator import BYTES_REDUCED

            out = np.concatenate(self.accs, axis=1)  # lint: guarded-ok: drain barrier read
            BYTES_REDUCED.labels(path="gather").inc(int(out.nbytes))
            return out
        import jax

        return jax.make_array_from_single_device_arrays(
            (self.agg.n_limbs, self.agg.padded_length),
            self.agg._acc_sharding,
            list(self.accs),  # lint: guarded-ok: drain barrier read
        )

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
