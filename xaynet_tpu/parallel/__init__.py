"""Device meshes and sharded aggregation.

The TPU answer to the reference's scaling story (reference:
rust/xaynet-server's single-threaded in-memory `Aggregation`): HBM-resident
accumulators sharded over the model axis of a `jax.sharding.Mesh`, with
zero-collective elementwise kernels and multi-host extensions.
"""

from .aggregator import ShardedAggregator
from .mesh import MODEL_AXIS, make_mesh, shard_slices
from .multihost import MultiHostAggregator
from .shards import ShardPlan
from .streaming import StreamingAggregator

__all__ = [
    "ShardedAggregator",
    "ShardPlan",
    "StreamingAggregator",
    "MODEL_AXIS",
    "make_mesh",
    "shard_slices",
    "MultiHostAggregator",
]
