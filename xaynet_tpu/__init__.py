"""xaynet_tpu — a TPU-native federated-learning framework (PET protocol).

A ground-up reimplementation of the capability surface of Xaynet
(masked, privacy-preserving cross-device federated learning) designed for
TPU hardware: the aggregation hot path (finite-group modular arithmetic over
multi-limb integer tensors, ChaCha20 mask expansion, unmasking) runs as
JAX/XLA/Pallas kernels over HBM-resident buffers and shards over a device
mesh via `jax.sharding`; the coordinator and participant runtimes are
host-side asyncio services speaking the PET wire protocol.

Layer map (mirrors the reference architecture, reimplemented TPU-first):

- ``xaynet_tpu.core``    — protocol kernel: crypto, masking math, wire format
- ``xaynet_tpu.ops``     — numpy / JAX / Pallas kernels for the hot loops
- ``xaynet_tpu.parallel``— device-mesh sharding of the aggregation buffers
- ``xaynet_tpu.server``  — coordinator: state machine, services, REST API
- ``xaynet_tpu.storage`` — coordinator/model storage backends
- ``xaynet_tpu.sdk``     — participant state machine + client
- ``xaynet_tpu.models``  — baseline model families with JAX local training
- ``xaynet_tpu.telemetry`` — metrics registry, kernel profiling, round reports
"""

__version__ = "0.1.0"
