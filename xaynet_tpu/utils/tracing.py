"""Request tracing: correlation ids across the service/state-machine boundary.

The reference instruments every request with a tracing span that travels
through the request channel so state-machine-side logs correlate with the
HTTP request that caused them (reference:
rust/xaynet-server/src/state_machine/requests.rs:120,157-165). Here the
span is a contextvar-scoped request id: the message pipeline assigns one
per message, the request envelope carries it across the queue, and the
phase restores it while handling — so a single grep on the id yields the
full path of one message through the system.
"""

from __future__ import annotations

import contextvars
import logging
import time
import uuid
from contextlib import contextmanager

request_id: contextvars.ContextVar[str] = contextvars.ContextVar("xaynet_request_id", default="-")

logger = logging.getLogger("xaynet.trace")


def new_request_id() -> str:
    rid = uuid.uuid4().hex[:12]
    request_id.set(rid)
    return rid


def current_request_id() -> str:
    return request_id.get()


@contextmanager
def use_request_id(rid: str):
    token = request_id.set(rid)
    try:
        yield
    finally:
        request_id.reset(token)


@contextmanager
def span(name: str, **fields):
    """Logs entry/exit with duration at DEBUG, tagged with the request id."""
    rid = request_id.get()
    extra = " ".join(f"{k}={v}" for k, v in fields.items())
    t0 = time.perf_counter()
    logger.debug("[%s] >> %s %s", rid, name, extra)
    try:
        yield
    except Exception as e:
        logger.debug(
            "[%s] !! %s failed after %.1fms: %s", rid, name, (time.perf_counter() - t0) * 1e3, e
        )
        raise
    else:
        logger.debug("[%s] << %s %.1fms", rid, name, (time.perf_counter() - t0) * 1e3)


class RequestIdFilter(logging.Filter):
    """Attach ``%(request_id)s`` to log records for formatter use."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = request_id.get()
        return True
