"""A minimal Lua 5.1 interpreter for Redis EVAL scripts.

The Redis coordinator storage guards its conditional inserts with Lua
scripts (``storage/redis.py``; reference:
rust/xaynet-server/src/storage/coordinator_storage/redis/mod.rs:208-343).
The test double used to *recognize those scripts by content* and run
equivalent Python — meaning the actual Lua text was never executed by any
interpreter and a syntax error would go unnoticed (VERDICT r02, missing
item 2). This module executes the real script text.

It implements the subset Redis scripting actually needs here, with Lua 5.1
semantics where they matter:

- values: nil, booleans, numbers (doubles), strings (Python ``bytes`` —
  Redis strings are binary-safe);
- 1-based table indexing of ``KEYS``/``ARGV``, the ``#`` length operator;
- ``local`` declarations, ``if/elseif/else``, numeric ``for`` with step,
  ``while``, ``return``, ``break``;
- operators: ``+ - * / %``, ``..``, ``== ~= < <= > >=``, ``and or not``
  (with Lua truthiness: only nil and false are falsy; ``and``/``or``
  return operands, not booleans);
- host functions: ``redis.call`` / ``redis.pcall``, ``tonumber``,
  ``tostring``, ``redis.error_reply``, ``redis.status_reply``;
- Redis type mapping on call results and on the final return value
  (number -> integer truncation, false -> nil, table -> array), exactly
  the conversion table documented for EVAL.

It is intentionally NOT a full Lua: no functions, closures, metatables,
goto, varargs, or the standard library beyond the functions above. Any
construct outside the subset raises ``LuaError`` at parse time — which is
precisely the point: a malformed script must fail loudly in tests.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Optional


class LuaError(Exception):
    """Raised for Lua syntax errors and runtime errors."""


# --------------------------------------------------------------------------
# Lexer
# --------------------------------------------------------------------------

_KEYWORDS = {
    "and", "break", "do", "else", "elseif", "end", "false", "for", "if",
    "in", "local", "nil", "not", "or", "repeat", "return", "then", "true",
    "until", "while", "function",
}

_TOKEN_RE = re.compile(
    rb"""
    (?P<ws>\s+)
  | (?P<comment>--\[\[.*?\]\]|--[^\n]*)
  | (?P<number>0[xX][0-9a-fA-F]+|\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?)
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<op>\.\.\.|\.\.|==|~=|<=|>=|[-+*/%#<>=(){}\[\];:,.])
    """,
    re.VERBOSE | re.DOTALL,
)

_ESCAPES = {
    b"n": b"\n", b"t": b"\t", b"r": b"\r", b"a": b"\a", b"b": b"\b",
    b"f": b"\f", b"v": b"\v", b"\\": b"\\", b'"': b'"', b"'": b"'",
    b"\n": b"\n", b"0": b"\x00",
}


@dataclass
class _Tok:
    kind: str  # 'number' | 'name' | 'string' | 'op' | 'keyword' | 'eof'
    value: object
    pos: int


def _unescape(raw: bytes) -> bytes:
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i : i + 1]
        if c == b"\\" and i + 1 < len(raw):
            nxt = raw[i + 1 : i + 2]
            if nxt.isdigit():  # \ddd decimal escapes
                j = i + 1
                while j < len(raw) and j < i + 4 and raw[j : j + 1].isdigit():
                    j += 1
                out.append(int(raw[i + 1 : j]))
                i = j
                continue
            if nxt in _ESCAPES:
                out += _ESCAPES[nxt]
                i += 2
                continue
            raise LuaError(f"invalid escape sequence \\{nxt.decode(errors='replace')}")
        out += c
        i += 1
    return bytes(out)


def _tokenize(src: bytes) -> list[_Tok]:
    toks: list[_Tok] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise LuaError(f"unexpected character {src[pos:pos+1]!r} at byte {pos}")
        pos = m.end()
        if m.lastgroup in ("ws", "comment"):
            continue
        text = m.group()
        if m.lastgroup == "number":
            toks.append(_Tok("number", float(int(text, 16)) if text[:2].lower() == b"0x" else float(text), m.start()))
        elif m.lastgroup == "name":
            name = text.decode()
            toks.append(_Tok("keyword" if name in _KEYWORDS else "name", name, m.start()))
        elif m.lastgroup == "string":
            toks.append(_Tok("string", _unescape(text[1:-1]), m.start()))
        else:
            toks.append(_Tok("op", text.decode(), m.start()))
    toks.append(_Tok("eof", None, len(src)))
    return toks


# --------------------------------------------------------------------------
# Parser -> AST (tuples: (kind, ...))
# --------------------------------------------------------------------------


class _Parser:
    def __init__(self, toks: list[_Tok]):
        self.toks = toks
        self.i = 0

    @property
    def cur(self) -> _Tok:
        return self.toks[self.i]

    def _advance(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def _expect(self, kind: str, value=None) -> _Tok:
        t = self.cur
        if t.kind != kind or (value is not None and t.value != value):
            raise LuaError(f"expected {value or kind}, got {t.value!r} at byte {t.pos}")
        return self._advance()

    def _check(self, kind: str, value=None) -> bool:
        t = self.cur
        return t.kind == kind and (value is None or t.value == value)

    def _accept(self, kind: str, value=None) -> bool:
        if self._check(kind, value):
            self._advance()
            return True
        return False

    # --- statements -------------------------------------------------------

    def parse_chunk(self, *terminators: str) -> list:
        stats = []
        while True:
            t = self.cur
            if t.kind == "eof" or (t.kind == "keyword" and t.value in terminators):
                return stats
            if self._accept("op", ";"):
                continue
            stats.append(self._statement())
            if stats[-1][0] in ("return", "break"):
                # nothing may follow a laststat in a block
                t = self.cur
                if not (t.kind == "eof" or (t.kind == "keyword" and t.value in terminators)):
                    raise LuaError(f"unreachable statement after {stats[-1][0]} at byte {t.pos}")
                return stats

    def _statement(self):
        t = self.cur
        if t.kind == "keyword":
            if t.value == "local":
                self._advance()
                name = self._expect("name").value
                self._expect("op", "=")
                return ("local", name, self._expr())
            if t.value == "if":
                return self._if()
            if t.value == "for":
                return self._for()
            if t.value == "while":
                self._advance()
                cond = self._expr()
                self._expect("keyword", "do")
                body = self.parse_chunk("end")
                self._expect("keyword", "end")
                return ("while", cond, body)
            if t.value == "return":
                self._advance()
                u = self.cur
                if u.kind == "eof" or (u.kind == "keyword" and u.value in ("end", "else", "elseif", "until")):
                    return ("return", None)
                return ("return", self._expr())
            if t.value == "break":
                self._advance()
                return ("break",)
            if t.value == "do":
                self._advance()
                body = self.parse_chunk("end")
                self._expect("keyword", "end")
                return ("do", body)
            raise LuaError(f"unsupported statement '{t.value}' at byte {t.pos}")
        # expression statement: function call or assignment
        e = self._postfix_expr()
        if self._accept("op", "="):
            if e[0] not in ("name", "index"):
                raise LuaError(f"cannot assign to {e[0]} at byte {t.pos}")
            return ("assign", e, self._expr())
        if e[0] != "call":
            raise LuaError(f"expression is not a statement at byte {t.pos}")
        return e

    def _if(self):
        self._expect("keyword", "if")
        arms = []
        cond = self._expr()
        self._expect("keyword", "then")
        arms.append((cond, self.parse_chunk("elseif", "else", "end")))
        while self._check("keyword", "elseif"):
            self._advance()
            c = self._expr()
            self._expect("keyword", "then")
            arms.append((c, self.parse_chunk("elseif", "else", "end")))
        els = None
        if self._accept("keyword", "else"):
            els = self.parse_chunk("end")
        self._expect("keyword", "end")
        return ("if", arms, els)

    def _for(self):
        self._expect("keyword", "for")
        var = self._expect("name").value
        self._expect("op", "=")
        start = self._expr()
        self._expect("op", ",")
        stop = self._expr()
        step = None
        if self._accept("op", ","):
            step = self._expr()
        self._expect("keyword", "do")
        body = self.parse_chunk("end")
        self._expect("keyword", "end")
        return ("for", var, start, stop, step, body)

    # --- expressions (precedence climbing) ---------------------------------

    def _expr(self):
        return self._or()

    def _or(self):
        e = self._and()
        while self._check("keyword", "or"):
            self._advance()
            e = ("or", e, self._and())
        return e

    def _and(self):
        e = self._cmp()
        while self._check("keyword", "and"):
            self._advance()
            e = ("and", e, self._cmp())
        return e

    def _cmp(self):
        e = self._concat()
        while self.cur.kind == "op" and self.cur.value in ("==", "~=", "<", "<=", ">", ">="):
            op = self._advance().value
            e = ("binop", op, e, self._concat())
        return e

    def _concat(self):
        e = self._add()
        if self._check("op", ".."):
            self._advance()
            return ("binop", "..", e, self._concat())  # right-associative
        return e

    def _add(self):
        e = self._mul()
        while self.cur.kind == "op" and self.cur.value in ("+", "-"):
            op = self._advance().value
            e = ("binop", op, e, self._mul())
        return e

    def _mul(self):
        e = self._unary()
        while self.cur.kind == "op" and self.cur.value in ("*", "/", "%"):
            op = self._advance().value
            e = ("binop", op, e, self._unary())
        return e

    def _unary(self):
        t = self.cur
        if t.kind == "op" and t.value in ("#", "-"):
            self._advance()
            return ("unop", t.value, self._unary())
        if t.kind == "keyword" and t.value == "not":
            self._advance()
            return ("unop", "not", self._unary())
        return self._postfix_expr()

    def _postfix_expr(self):
        e = self._primary()
        while True:
            if self._accept("op", "["):
                idx = self._expr()
                self._expect("op", "]")
                e = ("index", e, idx)
            elif self._accept("op", "."):
                name = self._expect("name").value
                e = ("index", e, ("const", name.encode()))
            elif self._check("op", "("):
                self._advance()
                args = []
                if not self._check("op", ")"):
                    args.append(self._expr())
                    while self._accept("op", ","):
                        args.append(self._expr())
                self._expect("op", ")")
                e = ("call", e, args)
            else:
                return e

    def _primary(self):
        t = self.cur
        if t.kind == "number":
            self._advance()
            return ("const", t.value)
        if t.kind == "string":
            self._advance()
            return ("const", t.value)
        if t.kind == "keyword" and t.value in ("nil", "true", "false"):
            self._advance()
            return ("const", {"nil": None, "true": True, "false": False}[t.value])
        if t.kind == "name":
            self._advance()
            return ("name", t.value)
        if self._accept("op", "("):
            e = self._expr()
            self._expect("op", ")")
            return e
        if self._accept("op", "{"):
            items = []
            if not self._check("op", "}"):
                items.append(self._expr())
                while self._accept("op", ","):
                    if self._check("op", "}"):
                        break
                    items.append(self._expr())
            self._expect("op", "}")
            return ("table", items)
        raise LuaError(f"unexpected token {t.value!r} at byte {t.pos}")


def parse(src: bytes):
    """Parse a script; raises ``LuaError`` on any syntax error."""
    return _Parser(_tokenize(src)).parse_chunk()


# --------------------------------------------------------------------------
# Evaluator
# --------------------------------------------------------------------------


class _Break(Exception):
    pass


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class LuaErrorReply:
    """``redis.error_reply(msg)``: converted to a RESP error on return."""

    def __init__(self, message: bytes):
        self.message = message


class LuaStatusReply:
    """``redis.status_reply(msg)``: converted to a RESP status on return."""

    def __init__(self, message: bytes):
        self.message = message


class LuaTable:
    """A Lua array-style table (1-based)."""

    def __init__(self, items: Optional[list] = None):
        self.items = list(items or [])

    def get(self, key):
        if isinstance(key, float) and key.is_integer():
            i = int(key)
            if 1 <= i <= len(self.items):
                return self.items[i - 1]
        return None

    def set(self, key, value):
        if not (isinstance(key, float) and key.is_integer()):
            raise LuaError("only integer table keys are supported")
        i = int(key)
        if i == len(self.items) + 1:
            self.items.append(value)
        elif 1 <= i <= len(self.items):
            self.items[i - 1] = value
        else:
            raise LuaError(f"sparse table assignment at index {i} is not supported")

    def __len__(self):
        return len(self.items)


def _truthy(v) -> bool:
    return v is not None and v is not False


def _type_name(v) -> str:
    if v is None:
        return "nil"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, float):
        return "number"
    if isinstance(v, bytes):
        return "string"
    if isinstance(v, LuaTable):
        return "table"
    return "userdata"


def _num_to_lua_string(n: float) -> bytes:
    if n.is_integer():
        return b"%d" % int(n)
    return repr(n).encode()


def _tonumber(v) -> Optional[float]:
    if isinstance(v, float):
        return v
    if isinstance(v, bytes):
        try:
            return float(v.strip())
        except ValueError:
            return None
    return None


class _Env:
    def __init__(self, parent: Optional["_Env"] = None):
        self.vars: dict[str, object] = {}
        self.parent = parent

    def lookup(self, name: str):
        env = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        raise LuaError(f"undefined variable '{name}'")

    def declare(self, name: str, value):
        self.vars[name] = value

    def assign(self, name: str, value):
        env = self
        while env is not None:
            if name in env.vars:
                env.vars[name] = value
                return
            env = env.parent
        raise LuaError(f"assignment to undeclared global '{name}' is not supported")


class _Interp:
    def __init__(self, globals_: dict[str, object]):
        self.root = _Env()
        self.root.vars.update(globals_)

    # --- statements -------------------------------------------------------

    def exec_block(self, stats: list, env: _Env) -> None:
        for st in stats:
            self.exec_stat(st, env)

    def exec_stat(self, st, env: _Env) -> None:
        kind = st[0]
        if kind == "local":
            env.declare(st[1], self.eval(st[2], env))
        elif kind == "assign":
            target, expr = st[1], st[2]
            value = self.eval(expr, env)
            if target[0] == "name":
                env.assign(target[1], value)
            else:  # index
                obj = self.eval(target[1], env)
                if not isinstance(obj, LuaTable):
                    raise LuaError(f"cannot index a {_type_name(obj)} value")
                obj.set(self.eval(target[2], env), value)
        elif kind == "if":
            for cond, body in st[1]:
                if _truthy(self.eval(cond, env)):
                    self.exec_block(body, _Env(env))
                    return
            if st[2] is not None:
                self.exec_block(st[2], _Env(env))
        elif kind == "for":
            _, var, start_e, stop_e, step_e, body = st
            start = self._want_number(self.eval(start_e, env), "'for' initial value")
            stop = self._want_number(self.eval(stop_e, env), "'for' limit")
            step = (
                self._want_number(self.eval(step_e, env), "'for' step")
                if step_e is not None
                else 1.0
            )
            if step == 0:
                raise LuaError("'for' step is zero")
            i = start
            try:
                while (step > 0 and i <= stop) or (step < 0 and i >= stop):
                    inner = _Env(env)
                    inner.declare(var, i)
                    self.exec_block(body, inner)
                    i += step
            except _Break:
                pass
        elif kind == "while":
            try:
                while _truthy(self.eval(st[1], env)):
                    self.exec_block(st[2], _Env(env))
            except _Break:
                pass
        elif kind == "do":
            self.exec_block(st[1], _Env(env))
        elif kind == "return":
            raise _Return(None if st[1] is None else self.eval(st[1], env))
        elif kind == "break":
            raise _Break()
        elif kind == "call":
            self.eval(st, env)
        else:  # pragma: no cover — parser only emits the kinds above
            raise LuaError(f"unknown statement kind {kind}")

    # --- expressions ------------------------------------------------------

    def eval(self, e, env: _Env):
        kind = e[0]
        if kind == "const":
            return e[1]
        if kind == "name":
            return env.lookup(e[1])
        if kind == "index":
            obj = self.eval(e[1], env)
            key = self.eval(e[2], env)
            if isinstance(obj, LuaTable):
                return obj.get(key)
            if isinstance(obj, dict):  # host namespace like `redis`
                name = key.decode() if isinstance(key, bytes) else key
                if name not in obj:
                    raise LuaError(f"unknown field '{name}'")
                return obj[name]
            raise LuaError(f"cannot index a {_type_name(obj)} value")
        if kind == "call":
            fn = self.eval(e[1], env)
            args = [self.eval(a, env) for a in e[2]]
            if not callable(fn):
                raise LuaError(f"cannot call a {_type_name(fn)} value")
            return fn(*args)
        if kind == "table":
            return LuaTable([self.eval(x, env) for x in e[1]])
        if kind == "and":
            left = self.eval(e[1], env)
            return self.eval(e[2], env) if _truthy(left) else left
        if kind == "or":
            left = self.eval(e[1], env)
            return left if _truthy(left) else self.eval(e[2], env)
        if kind == "unop":
            return self._unop(e[1], self.eval(e[2], env))
        if kind == "binop":
            return self._binop(e[1], self.eval(e[2], env), self.eval(e[3], env))
        raise LuaError(f"unknown expression kind {kind}")  # pragma: no cover

    @staticmethod
    def _want_number(v, what: str) -> float:
        n = _tonumber(v) if not isinstance(v, bool) else None
        if n is None:
            raise LuaError(f"{what} must be a number, got {_type_name(v)}")
        return n

    def _unop(self, op: str, v):
        if op == "#":
            if isinstance(v, bytes):
                return float(len(v))
            if isinstance(v, LuaTable):
                return float(len(v))
            raise LuaError(f"attempt to get length of a {_type_name(v)} value")
        if op == "-":
            return -self._want_number(v, "operand")
        if op == "not":
            return not _truthy(v)
        raise LuaError(f"unknown unary op {op}")  # pragma: no cover

    def _binop(self, op: str, a, b):
        if op in ("+", "-", "*", "/", "%"):
            x = self._want_number(a, "arithmetic operand")
            y = self._want_number(b, "arithmetic operand")
            if op == "+":
                return x + y
            if op == "-":
                return x - y
            if op == "*":
                return x * y
            if op == "/":
                if y == 0:
                    return float("inf") if x > 0 else float("-inf") if x < 0 else float("nan")
                return x / y
            return x - (x // y) * y if y != 0 else float("nan")  # Lua a%b
        if op == "..":
            parts = []
            for v in (a, b):
                if isinstance(v, bytes):
                    parts.append(v)
                elif isinstance(v, float):
                    parts.append(_num_to_lua_string(v))
                else:
                    raise LuaError(f"attempt to concatenate a {_type_name(v)} value")
            return parts[0] + parts[1]
        if op == "==":
            return self._lua_eq(a, b)
        if op == "~=":
            return not self._lua_eq(a, b)
        # ordering: number-number or string-string only (Lua 5.1 semantics)
        if isinstance(a, float) and isinstance(b, float):
            pass
        elif isinstance(a, bytes) and isinstance(b, bytes):
            pass
        else:
            raise LuaError(f"attempt to compare {_type_name(a)} with {_type_name(b)}")
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
        raise LuaError(f"unknown binary op {op}")  # pragma: no cover

    @staticmethod
    def _lua_eq(a, b) -> bool:
        # different types are never equal (no coercion in ==)
        if _type_name(a) != _type_name(b):
            return False
        if isinstance(a, LuaTable):
            return a is b
        return a == b


# --------------------------------------------------------------------------
# Redis EVAL front door
# --------------------------------------------------------------------------


def _from_redis(value):
    """RESP reply -> Lua value (Redis EVAL conversion rules)."""
    if value is None:
        return False  # RESP nil becomes Lua false
    if isinstance(value, int):
        return float(value)
    if isinstance(value, (bytes, bytearray)):
        return bytes(value)
    if isinstance(value, str):
        return value.encode()
    if isinstance(value, (list, tuple)):
        return LuaTable([_from_redis(v) for v in value])
    if isinstance(value, float):
        # Redis never returns floats from commands; scores arrive as strings
        return _num_to_lua_string(value)
    raise LuaError(f"unsupported redis reply type {type(value).__name__}")


def to_redis(value):
    """Lua value -> RESP reply (Redis EVAL conversion rules).

    An error reply raises ``LuaError`` so the RESP layer sends a ``-ERR``;
    a status reply becomes its message (the fake encodes bytes as bulk,
    which the client reads equivalently to a simple status here).
    """
    if isinstance(value, LuaErrorReply):
        raise LuaError(value.message.decode(errors="replace"))
    if isinstance(value, LuaStatusReply):
        return value.message
    if value is None or value is False:
        return None
    if value is True:
        return 1
    if isinstance(value, float):
        return int(value)  # truncation, as Redis does
    if isinstance(value, bytes):
        return value
    if isinstance(value, LuaTable):
        out = []
        for v in value.items:
            if v is None or v is False:
                break  # a nil ends the array, per Redis conversion rules
            out.append(to_redis(v))
        return out
    raise LuaError(f"unsupported return type {_type_name(value)}")


def run_script(
    script: bytes,
    keys: list[bytes],
    argv: list[bytes],
    call: Callable[..., object],
) -> object:
    """Execute ``script`` with ``KEYS``/``ARGV`` bound and ``redis.call`` -> ``call``.

    ``call`` receives the command arguments as bytes and returns a RESP-style
    value (int, bytes, None, or list). The return value is converted with the
    EVAL conversion rules (``to_redis``). Raises ``LuaError`` on syntax or
    runtime errors — including errors raised by ``call`` itself (as
    ``redis.call`` does; ``redis.pcall`` would catch them, and is mapped to
    the same host function since the scripts here never rely on catching).
    """
    ast = parse(script)

    def lua_call(*args):
        if not args:
            raise LuaError("redis.call needs at least one argument")
        cmd_args = []
        for a in args:
            if isinstance(a, bytes):
                cmd_args.append(a)
            elif isinstance(a, float):
                cmd_args.append(_num_to_lua_string(a))
            else:
                raise LuaError(
                    f"redis.call argument must be a string or number, got {_type_name(a)}"
                )
        return _from_redis(call(*cmd_args))

    def lua_tonumber(v, base=None):
        if base is not None:
            if not isinstance(v, bytes):
                return None
            try:
                return float(int(v, int(base)))
            except ValueError:
                return None
        return _tonumber(v)

    def lua_tostring(v):
        if isinstance(v, bytes):
            return v
        if isinstance(v, float):
            return _num_to_lua_string(v)
        if v is None:
            return b"nil"
        if isinstance(v, bool):
            return b"true" if v else b"false"
        return _type_name(v).encode()

    interp = _Interp(
        {
            "KEYS": LuaTable(list(keys)),
            "ARGV": LuaTable(list(argv)),
            "redis": {
                "call": lua_call,
                "pcall": lua_call,
                "error_reply": lambda msg: LuaErrorReply(msg),
                "status_reply": lambda msg: LuaStatusReply(msg),
            },
            "tonumber": lua_tonumber,
            "tostring": lua_tostring,
        }
    )
    try:
        interp.exec_block(ast, _Env(interp.root))
    except _Return as r:
        return to_redis(r.value)
    except _Break:
        raise LuaError("break outside of a loop")
    return None
