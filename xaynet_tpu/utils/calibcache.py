"""Persisted kernel auto-calibration verdicts (docs/DESIGN.md §22).

The fold race (``parallel.aggregator._resolve_kernel``) and the mask race
(``ops.masking_jax._resolve_mask_kernel``) memoize their winners
process-wide — but a FRESH process still pays the probe race inside its
first round's wall. This module gives those memos a disk tier: verdicts
are keyed exactly like the in-process caches and stamped with an
environment fingerprint (backend, jax version, core count, native-kernel
ABI, thread pins, mesh shape is already part of each verdict key), so a
restarted coordinator starts its first round with the winners it raced
last time. A fingerprint mismatch — new jax, rebuilt native library,
different machine — invalidates the whole file: stale verdicts silently
misrouting a kernel would be worse than re-racing.

Off by default. Enable by pointing ``XAYNET_CALIB_CACHE`` at a JSON file
(the runner and the bench both honor it); ``configure(path)`` does the
same programmatically. Writes are atomic (tempfile + rename), loads are
fail-soft: a corrupt or unreadable cache logs and behaves like a cold
one.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading

logger = logging.getLogger(__name__)

ENV_PATH = "XAYNET_CALIB_CACHE"

_lock = threading.Lock()
_path: str | None = None
_verdicts: dict[str, dict[str, str]] = {}  # kind -> {key repr -> winner}
_loaded_for: str | None = None  # fingerprint the loaded verdicts belong to


def fingerprint() -> str:
    """The environment identity a verdict is only valid within."""
    import jax

    from . import native

    lib = native.load()
    abi = int(lib.xn_abi_version()) if lib is not None else None
    parts = {
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "devices": jax.device_count(),
        "cpus": os.cpu_count(),
        "native_abi": abi,
        "native_threads": os.environ.get("XAYNET_NATIVE_THREADS", ""),
    }
    return json.dumps(parts, sort_keys=True)


def configure(path: str | None) -> None:
    """Point the cache at ``path`` (None disables) and load it eagerly —
    the serve-start hook, so the first round's kernel resolution finds
    warm verdicts instead of racing inside its round wall."""
    global _path, _verdicts, _loaded_for
    with _lock:
        _path = path or None
        _verdicts = {}
        _loaded_for = None
        if _path is None:
            return
        fp = fingerprint()
        _loaded_for = fp
        try:
            with open(_path, encoding="utf-8") as f:
                raw = json.load(f)
        except FileNotFoundError:
            logger.info("calibration cache %s: cold start", _path)
            return
        except Exception as e:
            logger.warning("calibration cache %s unreadable (%s); cold start", _path, e)
            return
        if raw.get("fingerprint") != fp:
            logger.info(
                "calibration cache %s: fingerprint changed, verdicts invalidated",
                _path,
            )
            return
        verdicts = raw.get("verdicts")
        if isinstance(verdicts, dict):
            _verdicts = {
                kind: dict(v) for kind, v in verdicts.items() if isinstance(v, dict)
            }
            n = sum(len(v) for v in _verdicts.values())
            logger.info("calibration cache %s: %d warm verdicts", _path, n)


def configure_from_env() -> None:
    configure(os.environ.get(ENV_PATH, ""))


def get(kind: str, key: tuple) -> str | None:
    """Warm verdict for a race the process has not run yet, or None."""
    with _lock:
        if _path is None:
            return None
        return _verdicts.get(kind, {}).get(repr(key))


def put(kind: str, key: tuple, winner: str) -> None:
    """Record a freshly-raced verdict and persist the file atomically."""
    with _lock:
        if _path is None:
            return
        _verdicts.setdefault(kind, {})[repr(key)] = winner
        payload = {"fingerprint": _loaded_for or fingerprint(), "verdicts": _verdicts}
        try:
            d = os.path.dirname(os.path.abspath(_path))
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".calib-", suffix=".json")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(payload, f, indent=1, sort_keys=True)
                os.replace(tmp, _path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:
            logger.warning("calibration cache %s not persisted: %s", _path, e)
