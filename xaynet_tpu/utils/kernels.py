"""Fold-kernel registry.

Single source of truth for the aggregation fold kernel names, shared by
``parallel.aggregator`` (which executes them) and ``server.settings`` (which
validates configs without importing jax).
"""

FOLD_KERNELS = ("auto", "xla", "pallas", "pallas-interpret")
