"""Fold-kernel registry.

Single source of truth for the aggregation fold kernel names, shared by
``parallel.aggregator`` (which executes them) and ``server.settings`` (which
validates configs without importing jax).

``native-u64`` is the host C++ single-pass fold (``utils.native`` /
``native/xaynet_native.cpp``): threaded over the element axis, it beats the
XLA CPU fold ~2.5x at the 25M-param bench shape, so ``auto`` races it
against XLA on CPU backends (<= 2-limb orders). Multi-device meshes are
served too: each device's contiguous plane slice folds through the strided
kernel entry under a per-shard thread budget — sequentially via one
concurrent slice call per shard, and in the streaming pipeline via one
fold worker per shard (``parallel.shards``). It degrades to ``xla``
cleanly when the shared library won't build.
"""

FOLD_KERNELS = ("auto", "xla", "pallas", "pallas-interpret", "native-u64")

# Sum2 mask derive+sum kernels (``ops.masking_jax.sum_masks``):
#
# - ``batch``        — ALL derivations of a seed group in ONE jitted in-graph
#                      program (``derive_mask_limbs_batch``), the resulting
#                      mask planes streamed through the PR-7 shard pipeline;
# - ``fused-pallas`` — the Pallas keystream→reject→modular-add kernel
#                      (``ops.fold_pallas.mask_fold_planar_pallas``): the mask
#                      is never materialized in HBM, only the accumulator is;
# - ``fused-pallas-interpret`` — the same kernel through the Pallas
#                      interpreter (the CPU route that keeps the fused kernel
#                      continuously exercised without a Mosaic compiler);
# - ``host-threaded`` — the CPU incumbent: the fused native sample+fold
#                      (``xn_sample_fold_u64`` — accepted draws accumulate
#                      straight into a u64 buffer, the mask never
#                      materializes) when the order fits, else the native
#                      (AVX2) ``StreamSampler`` across a GIL-released
#                      thread pool with the single-pass batch fold;
# - ``host-chunked`` — the pre-promotion device path (host unit draws per
#                      seed + host-chunked device vector derivation), kept
#                      as an explicit fallback;
# - ``auto``         — first call races the candidates on a probe seed group
#                      (the fold-kernel auto-calibration idiom) and memoizes
#                      the winner process-wide.
MASK_KERNELS = (
    "auto",
    "batch",
    "fused-pallas",
    "fused-pallas-interpret",
    "host-threaded",
    "host-chunked",
)
