"""Fold-kernel registry.

Single source of truth for the aggregation fold kernel names, shared by
``parallel.aggregator`` (which executes them) and ``server.settings`` (which
validates configs without importing jax).

``native-u64`` is the host C++ single-pass fold (``utils.native`` /
``native/xaynet_native.cpp``): threaded over the element axis, it beats the
XLA CPU fold ~2.5x at the 25M-param bench shape, so ``auto`` races it
against XLA on CPU backends (<= 2-limb orders). Multi-device meshes are
served too: each device's contiguous plane slice folds through the strided
kernel entry under a per-shard thread budget — sequentially via one
concurrent slice call per shard, and in the streaming pipeline via one
fold worker per shard (``parallel.shards``). It degrades to ``xla``
cleanly when the shared library won't build.
"""

FOLD_KERNELS = ("auto", "xla", "pallas", "pallas-interpret", "native-u64")
