"""Loader for the native host kernels (ctypes, lazy on-demand build).

``libxaynet_native.so`` is built from ``native/xaynet_native.cpp`` on first
use (plain ``make``; no network). Everything has a pure-Python/numpy
fallback — set ``XAYNET_TPU_NO_NATIVE=1`` to force it.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

logger = logging.getLogger("xaynet.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libxaynet_native.so")

_ABI_VERSION = 8

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # build ONLY the kernel library this loader consumes — the participant
    # library additionally links libsodium, which may be absent on hosts
    # that only need the numpy-fallback-compatible kernels
    for args in (
        ["make", "-s", "libxaynet_native.so"],
        ["make", "-s", "libxaynet_native.so", "ARCHFLAGS="],
    ):
        try:
            subprocess.run(
                args, cwd=_NATIVE_DIR, check=True, capture_output=True, timeout=120
            )
            return True
        except Exception as e:  # retry without SIMD flags, then give up
            logger.debug("native build failed (%s): %s", args, e)
    return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None when unavailable/disabled."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("XAYNET_TPU_NO_NATIVE"):
        return None
    # rebuild BEFORE the first dlopen: once a (stale) library is loaded,
    # re-dlopening the same path returns the already-loaded image, so the
    # staleness check must be mtime-based, not load-and-inspect
    if os.path.isdir(_NATIVE_DIR):
        src = os.path.join(_NATIVE_DIR, "xaynet_native.cpp")
        stale = os.path.exists(src) and (
            not os.path.exists(_LIB_PATH)
            or os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
        )
        if stale:
            _build()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        if lib.xn_abi_version() != _ABI_VERSION:
            logger.warning("native library ABI mismatch; using python fallback")
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.xn_chacha20_blocks.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, u8p]
        lib.xn_chacha20_blocks.restype = None
        lib.xn_sample_uniform.argtypes = [
            u8p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            u8p,
            ctypes.c_uint32,
            u8p,
        ]
        lib.xn_sample_uniform.restype = ctypes.c_uint64
        # fused sample+fold (ABI 7): accepted draws accumulate into a u64
        # buffer instead of materializing the mask bytes
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.xn_sample_fold_u64.argtypes = [
            u8p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            u8p,
            ctypes.c_uint32,
            u64p,
        ]
        lib.xn_sample_fold_u64.restype = ctypes.c_uint64
        lib.xn_mod_add.argtypes = [u32p, u32p, u32p, ctypes.c_uint64, ctypes.c_uint32, u32p]
        lib.xn_mod_add.restype = None
        lib.xn_fold_planar_u64.argtypes = [
            u32p,
            u32p,
            u32p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.c_uint64,
            u32p,
        ]
        lib.xn_fold_planar_u64.restype = None
        lib.xn_fold_wire_u64.argtypes = list(lib.xn_fold_planar_u64.argtypes)
        lib.xn_fold_wire_u64.restype = None
        # strided slice fold: pointers pre-offset to the slice start, plane
        # and batch strides in ELEMENTS, explicit per-call thread budget
        lib.xn_fold_planar_u64_strided.argtypes = [
            u32p,
            u32p,
            u32p,
            ctypes.c_uint64,  # width
            ctypes.c_uint64,  # acc/out plane stride
            ctypes.c_uint64,  # stack row (limb-plane) stride
            ctypes.c_uint64,  # stack batch (update) stride
            ctypes.c_uint32,  # n_limbs
            ctypes.c_uint64,  # k
            u32p,
            ctypes.c_uint32,  # n_threads (0 = process default)
        ]
        lib.xn_fold_planar_u64_strided.restype = None
        # packed byte-planar fold (ABI 8): the staged batch arrives as
        # uint8[K, bpn, n] byte planes (ops/limbs.py pack_planar) and folds
        # into the planar u32 accumulator without ever unpacking
        lib.xn_fold_packed_u64_strided.argtypes = [
            u32p,
            u8p,
            u32p,
            ctypes.c_uint64,  # width
            ctypes.c_uint64,  # acc/out plane stride (elements)
            ctypes.c_uint64,  # packed byte-plane stride (bytes)
            ctypes.c_uint64,  # packed batch (update) stride (bytes)
            ctypes.c_uint32,  # n_limbs
            ctypes.c_uint32,  # bpn
            ctypes.c_uint64,  # k
            u32p,
            ctypes.c_uint32,  # n_threads (0 = process default)
        ]
        lib.xn_fold_packed_u64_strided.restype = None
        lib.xn_pack_wire_planes.argtypes = [
            u32p,
            ctypes.c_uint64,  # n elements
            ctypes.c_uint32,  # n_limbs (element stride in u32)
            ctypes.c_uint32,  # bpn
            u8p,
            ctypes.c_uint64,  # out plane stride (bytes)
            ctypes.c_uint32,  # n_threads (0 = process default)
        ]
        lib.xn_pack_wire_planes.restype = None
        lib.xn_pack_planar_planes.argtypes = [
            u32p,
            ctypes.c_uint64,  # n elements
            ctypes.c_uint64,  # input plane stride (u32 elements)
            ctypes.c_uint32,  # bpn
            u8p,
            ctypes.c_uint64,  # out plane stride (bytes)
            ctypes.c_uint32,  # n_threads
        ]
        lib.xn_pack_planar_planes.restype = None
        lib.xn_fold_threads.argtypes = []
        lib.xn_fold_threads.restype = ctypes.c_uint32
        lib.xn_mod_sub.argtypes = [u32p, u32p, u32p, ctypes.c_uint64, ctypes.c_uint32, u32p]
        lib.xn_mod_sub.restype = None
        lib.xn_decode_f64.argtypes = [
            u32p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            u8p,
            ctypes.c_uint32,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.xn_decode_f64.restype = ctypes.c_int
        lib.xn_decode_exact.argtypes = [
            u32p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            u32p,
            ctypes.c_uint32,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.xn_decode_exact.restype = ctypes.c_int
        lib.xn_mask_f32.argtypes = [
            u8p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_uint64,
            u8p,
            ctypes.c_uint32,
            ctypes.c_uint32,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_double,
            u8p,
        ]
        lib.xn_mask_f32.restype = ctypes.c_uint64
        lib.xn_wire_to_limbs.argtypes = [
            u8p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.c_uint32,
            u32p,
        ]
        lib.xn_wire_to_limbs.restype = None
        lib.xn_limbs_to_wire.argtypes = [
            u32p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.c_uint32,
            u8p,
        ]
        lib.xn_limbs_to_wire.restype = None
        lib.xn_count_ge.argtypes = [u32p, ctypes.c_uint64, ctypes.c_uint32, u32p]
        lib.xn_count_ge.restype = ctypes.c_uint64
        lib.xn_fold_wire_nlimb.argtypes = list(lib.xn_fold_wire_u64.argtypes)
        lib.xn_fold_wire_nlimb.restype = ctypes.c_int
        _lib = lib
    except (OSError, AttributeError) as e:
        # AttributeError: a stale prebuilt .so missing newer symbols when the
        # rebuild could not run — degrade to the python fallback, not a crash
        logger.warning("native library load failed; using python fallback: %s", e)
        _lib = None
    return _lib


def as_u8p(buf) -> "ctypes.pointer":
    return ctypes.cast(ctypes.c_char_p(bytes(buf)), ctypes.POINTER(ctypes.c_uint8))


def np_u8p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def np_u32p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def np_u64p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def np_u8p_at(arr, byte_offset: int):
    """Pointer to ``arr``'s buffer offset by ``byte_offset`` bytes (the
    packed-plane twin of :func:`np_u32p_at`)."""
    return ctypes.cast(
        ctypes.c_void_p(arr.ctypes.data + byte_offset),
        ctypes.POINTER(ctypes.c_uint8),
    )


def np_u32p_at(arr, element_offset: int):
    """Pointer to ``arr``'s buffer offset by ``element_offset`` uint32
    elements — how the strided slice kernels address one shard's column
    slice of a larger C-contiguous array without materializing a copy."""
    return ctypes.cast(
        ctypes.c_void_p(arr.ctypes.data + 4 * element_offset),
        ctypes.POINTER(ctypes.c_uint32),
    )
