"""Persistent-XLA-cache policy shared by every bench entry point.

On CPU the persistent compilation cache is a net negative for this fleet:
the shared-container hosts migrate between machine types, so a cached CPU
executable regularly fails XLA's machine-feature check and every load
spews the multi-KB "CPU compilation doesn't match the machine type ...
could lead to execution errors such as SIGILL" warning over the bench
tail, while CPU kernels recompile in seconds anyway. Merely *not
enabling* the cache is not enough — the image's sitecustomize (or an
inherited ``JAX_COMPILATION_CACHE_DIR``) can switch it on before the
bench runs — so this helper ACTIVELY disables it. Accelerator backends
keep their cache (a brief tunnel-up window must not be spent recompiling
kernels a previous capture already built).
"""

from __future__ import annotations

import os


def silence_cpu_cache(jax) -> bool:
    """Disable the persistent XLA compilation cache when the backend is
    CPU. Call right after importing jax (and pinning the platform), before
    the first compile. Returns True when the cache was disabled. Never
    raises — cache policy is an optimization, not a failure mode."""
    try:
        if jax.default_backend() != "cpu":
            return False
    except Exception:
        return False
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    try:
        jax.config.update("jax_enable_compilation_cache", False)
    except Exception:
        # very old/new jax without the master switch: clearing the cache
        # dir reaches the same end
        try:
            jax.config.update("jax_compilation_cache_dir", "")
        except Exception:
            return False
    return True
