"""Tenant registry: specs, per-tenant runtime contexts, admission budgets.

One coordinator process serves N tenants. Each tenant is a full,
independent PET round pipeline — its own settings (mask config, model
length, liveness policy), its own scoped store, its own phase state
machine, request channel and ingest pipeline — while the process-level
resources (the mesh, the accumulator page pool, the fold-batch scheduler,
the REST listener, the telemetry registry) are shared. The registry owns
the id -> context mapping the REST layer routes ``/t/<tenant>/...`` by.

The **admission budget** layers per-tenant quotas on top of the PR-2
``AdmissionController``: the controller still owns each tenant's
watermark hysteresis over its own intake shards; the budget bounds any
single tenant's share of the PROCESS-wide in-queue message total, so a
flooding tenant sheds (429 + Retry-After) before it can crowd the other
tenants' decrypt capacity.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

from ..telemetry.registry import get_registry

DEFAULT_TENANT = "default"

_TENANT_ID_RE = re.compile(r"^[a-z0-9][a-z0-9_-]{0,31}$")

_registry = get_registry()
TENANT_INGEST_SHED = _registry.counter(
    "xaynet_tenant_ingest_shed_total",
    "Messages shed by the per-tenant admission budget (tenant over its "
    "share of the process-wide intake), by tenant.",
    ("tenant",),
)
TENANT_INGEST_OCCUPANCY = _registry.gauge(
    "xaynet_tenant_ingest_occupancy",
    "Messages a tenant currently holds in the process-wide intake, "
    "by tenant.",
    ("tenant",),
)


def validate_tenant_id(tenant: str) -> str:
    """Tenant ids are routing tokens, metric label values and storage key
    prefixes at once: lowercase alphanumerics plus ``-``/``_``, at most 32
    chars, never empty."""
    if not _TENANT_ID_RE.match(tenant):
        raise ValueError(
            f"invalid tenant id {tenant!r}: want ^[a-z0-9][a-z0-9_-]{{0,31}}$"
        )
    return tenant


@dataclass
class TenantContext:
    """One tenant's live runtime surface (built by the runner)."""

    tenant: str
    settings: Any
    store: Any = None
    machine: Any = None
    request_tx: Any = None
    events: Any = None
    handler: Any = None
    fetcher: Any = None
    pipeline: Any = None  # ingest.IngestPipeline or None
    edge_api: Any = None
    metrics: Any = None
    task: Any = None  # the state machine's asyncio task
    extra: dict = field(default_factory=dict)


class TenantRegistry:
    """Ordered id -> context map; the first registered tenant is the
    *default* (it also serves the unprefixed legacy routes)."""

    def __init__(self):
        self._contexts: dict[str, TenantContext] = {}
        self._lock = threading.Lock()

    def add(self, ctx: TenantContext) -> TenantContext:
        validate_tenant_id(ctx.tenant)
        with self._lock:
            if ctx.tenant in self._contexts:
                raise ValueError(f"tenant {ctx.tenant!r} already registered")
            self._contexts[ctx.tenant] = ctx
        return ctx

    def remove(self, tenant: str) -> Optional[TenantContext]:
        """Unregister a drained tenant (lifecycle offboard). Returns the
        removed context, or None if it was never (or no longer)
        registered. The first-registered tenant stays the default for the
        life of the process — offboarding it leaves the unprefixed legacy
        routes pointing at the next-oldest tenant."""
        with self._lock:
            return self._contexts.pop(tenant, None)

    def get(self, tenant: str) -> Optional[TenantContext]:
        with self._lock:
            return self._contexts.get(tenant)

    def ids(self) -> list[str]:
        with self._lock:
            return list(self._contexts)

    def contexts(self) -> list[TenantContext]:
        with self._lock:
            return list(self._contexts.values())

    @property
    def default(self) -> Optional[TenantContext]:
        with self._lock:
            return next(iter(self._contexts.values()), None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._contexts)


class TenantAdmissionBudget:
    """Per-tenant share of the process-wide intake occupancy.

    ``charge(tenant)`` accounts one admitted message and returns False —
    shed — when the tenant would exceed ``max_share`` of ``capacity``;
    ``discharge(tenant, n)`` returns capacity as the tenant's decrypt
    workers drain. The budget sits IN FRONT of the tenant's own
    ``AdmissionController`` (which still applies its watermark hysteresis
    to what the budget admits)."""

    def __init__(self, capacity: int, max_share: float = 0.6):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if not (0.0 < max_share <= 1.0):
            raise ValueError("max_share must be in (0, 1]")
        self.capacity = capacity
        self.max_share = max_share
        # ceil, and never below 1: a tiny capacity must not 0-out a tenant
        self.per_tenant = max(1, int(capacity * max_share))
        self._lock = threading.Lock()
        self._held: dict[str, int] = {}  # guarded-by: _lock

    def charge(self, tenant: str) -> bool:
        with self._lock:
            held = self._held.get(tenant, 0)
            total = sum(self._held.values())
            if held >= self.per_tenant or total >= self.capacity:
                TENANT_INGEST_SHED.labels(tenant=tenant).inc()
                return False
            self._held[tenant] = held + 1
        TENANT_INGEST_OCCUPANCY.labels(tenant=tenant).inc()
        return True

    def discharge(self, tenant: str, n: int = 1) -> None:
        with self._lock:
            held = self._held.get(tenant, 0)
            n = min(n, held)
            if n <= 0:
                return
            self._held[tenant] = held - n
        TENANT_INGEST_OCCUPANCY.labels(tenant=tenant).dec(n)

    def held(self, tenant: str) -> int:
        with self._lock:
            return self._held.get(tenant, 0)
