"""Multi-tenant coordinator plumbing (docs/DESIGN.md §19, §23).

- :mod:`pool` — the paged accumulator pool: fixed-size pages, host slab
  arena + device capacity ledger, per-tenant page tables, lease/release
  accounting with the round-end leases == releases invariant, and
  between-round compaction of fragmented slabs.
- :mod:`scheduler` — the tenant fold-batch scheduler: bounded in-flight
  slots across tenants, weighted deficit-round-robin fairness with
  priority tiers and SLO-fed demotion, the round report's fairness split.
- :mod:`registry` — tenant specs/contexts, id validation, and the
  per-tenant admission budget layered on the ingest pipeline.
- :mod:`lifecycle` — the elastic tenant lifecycle: runtime
  onboard/drain, fault quarantine over per-tenant breakers, SLO-weighted
  preemption feedback.
"""

from .lifecycle import (
    LifecycleError,
    TenantLifecycle,
    get_manager,
    install_manager,
)
from .pool import PageLease, PagePool, PoolExhausted, configure_pool, get_pool
from .registry import (
    DEFAULT_TENANT,
    TenantAdmissionBudget,
    TenantContext,
    TenantRegistry,
    validate_tenant_id,
)
from .scheduler import TenantScheduler, configure_scheduler, get_scheduler

__all__ = [
    "DEFAULT_TENANT",
    "LifecycleError",
    "PageLease",
    "PagePool",
    "PoolExhausted",
    "TenantAdmissionBudget",
    "TenantContext",
    "TenantLifecycle",
    "TenantRegistry",
    "TenantScheduler",
    "configure_pool",
    "configure_scheduler",
    "get_manager",
    "get_pool",
    "get_scheduler",
    "install_manager",
    "validate_tenant_id",
]
