"""Multi-tenant coordinator plumbing (docs/DESIGN.md §19).

- :mod:`pool` — the paged accumulator pool: fixed-size pages, host slab
  arena + device capacity ledger, per-tenant page tables, lease/release
  accounting with the round-end leases == releases invariant.
- :mod:`scheduler` — the tenant fold-batch scheduler: bounded in-flight
  slots across tenants, deficit-round-robin fairness, the round report's
  fairness split.
- :mod:`registry` — tenant specs/contexts, id validation, and the
  per-tenant admission budget layered on the ingest pipeline.
"""

from .pool import PageLease, PagePool, PoolExhausted, configure_pool, get_pool
from .registry import (
    DEFAULT_TENANT,
    TenantAdmissionBudget,
    TenantContext,
    TenantRegistry,
    validate_tenant_id,
)
from .scheduler import TenantScheduler, configure_scheduler, get_scheduler

__all__ = [
    "DEFAULT_TENANT",
    "PageLease",
    "PagePool",
    "PoolExhausted",
    "TenantAdmissionBudget",
    "TenantContext",
    "TenantRegistry",
    "TenantScheduler",
    "configure_pool",
    "configure_scheduler",
    "get_pool",
    "get_scheduler",
    "validate_tenant_id",
]
