"""Elastic tenant lifecycle: live onboard/drain, quarantine, SLO feedback.

PR 15 froze the tenant set at process start; this module makes it elastic
(docs/DESIGN.md §23). One ``TenantLifecycle`` manager per multi-tenant
process owns the per-tenant state machine

    drained -> onboarding -> serving <-> quarantined
                                 \\-> draining -> drained

and the three control loops around it:

- **onboard/offboard** — the authenticated ``/admin/tenants`` REST
  surface calls :meth:`onboard` (build the tenant's full round pipeline
  via the runner's builder, warm the persisted kernel-calibration tier so
  the first round skips the probe race, THEN register routes and admit
  traffic) and :meth:`offboard` (graceful drain: stop admission, let the
  in-flight round finish or degraded-close per the PR-5 quorum/stall
  semantics, then tear down — task cancel, channel close, pipeline stop,
  page reclaim, unregister — with a hard-kill escalation and a flight
  bundle when the drain budget runs out).
- **fault quarantine** — each tenant gets a ``resilience.CircuitBreaker``
  fed by round outcomes (``note_round_failed`` / ``note_round_completed``
  from the phase close paths). Repeated failures — a storage breaker
  stuck open fails its rounds, a poisoned pipeline fails its rounds — trip
  the breaker OPEN: the tenant's ingress sheds with 429s, its scheduler
  priority is demoted, and a forensic flight bundle with scrubbed
  per-tenant counter deltas is written. Recovery is the breaker's own
  half-open probing: after ``quarantine_reset_s`` the next round's traffic
  is admitted as a probe; a completed round closes the breaker and
  restores the tenant, a failed one re-opens it. While the breaker is
  OPEN, round outcomes are NOT recorded — a shed tenant's timeout failures
  are self-inflicted and must not hold the quarantine open forever, and a
  degraded-close of pre-quarantine traffic must not end it early.
- **SLO-weighted preemption** — the PR-16 burn-rate engine reports every
  severity transition here (``slo.set_transition_hook``); a tenant paging
  on any SLO is demoted in the fold-batch scheduler (it only receives
  slots no healthy tenant wants) and restored the moment the burn
  recovers. Configured ``[tenancy] weights``/``tiers`` apply at
  serving-entry.

Quarantine deliberately does NOT force-reclaim the tenant's pool pages
mid-round: in-flight fold threads hold live numpy views into the slabs,
and freeing + re-leasing those runs to another tenant would corrupt both.
Pages return at the tenant's own round boundary (``Idle._reconcile_pool``
gc + reclaim), scheduler slots via the pipeline's owner release — the
isolation guarantee is *admission* (shed at the door) plus *priority*
(demoted in the scheduler), both effective immediately.
"""

from __future__ import annotations

import asyncio
import gc
import logging
import threading
import time
from typing import Any, Callable, Optional

from ..resilience.breaker import OPEN, CircuitBreaker
from ..telemetry.recorder import flight_dump
from ..telemetry.redact import scrub_attrs
from ..telemetry.registry import get_registry
from .pool import get_pool
from .registry import TenantRegistry, validate_tenant_id
from .scheduler import get_scheduler

logger = logging.getLogger("xaynet.tenancy")

_registry = get_registry()
TENANT_STATE = _registry.gauge(
    "xaynet_tenant_state",
    "Lifecycle state per tenant (0 = drained, 1 = onboarding, 2 = serving, "
    "3 = quarantined, 4 = draining; docs/DESIGN.md §23).",
    ("tenant",),
)
TENANT_QUARANTINES = _registry.counter(
    "xaynet_tenant_quarantines_total",
    "Fault quarantines tripped, by tenant (repeated round failures opened "
    "the tenant's breaker; traffic sheds until the half-open probe round "
    "completes).",
    ("tenant",),
)
TENANT_DRAINS = _registry.counter(
    "xaynet_tenant_drains_total",
    "Tenant drains finished, by outcome (graceful = the in-flight round "
    "closed inside the budget; timeout = hard-kill escalation).",
    ("outcome",),
)

DRAINED = "drained"
ONBOARDING = "onboarding"
SERVING = "serving"
QUARANTINED = "quarantined"
DRAINING = "draining"
_STATE_VALUE = {DRAINED: 0, ONBOARDING: 1, SERVING: 2, QUARANTINED: 3, DRAINING: 4}

# per-tenant counter families sampled into quarantine/drain flight bundles
# (deltas since the tenant last entered serving — the rounds that spent
# the failure budget); entries are (family, extra labels, short name)
_DELTA_FAMILIES = (
    ("xaynet_tenant_fold_batches_total", {}, "fold_batches"),
    ("xaynet_tenant_ingest_shed_total", {}, "ingest_shed"),
    ("xaynet_pool_reclaimed_total", {}, "pool_reclaims"),
    ("xaynet_pool_pages", {"arena": "host"}, "host_pages_held"),
)


class LifecycleError(RuntimeError):
    """An admin-path transition was requested from an incompatible state
    (onboarding a live tenant, draining one that is not serving, ...)."""


class TenantLifecycle:
    """Per-process elastic tenancy manager (docs/DESIGN.md §23).

    ``builder`` is the runner's async factory: ``await builder(tenant)``
    builds the tenant's full round pipeline (scoped store, channels,
    machine, pipeline, edge api), registers it in ``registry`` and returns
    ``(TenantContext, TenantRoutes)``. ``routes`` is the LIVE dict the
    RestServer routes ``/t/<tenant>/...`` by — mutating it here is what
    makes onboard/offboard take effect without a restart. ``clock`` is
    injectable so lifecycle tests don't sleep through drain budgets.
    """

    def __init__(
        self,
        settings: Any,  # TenancySettings
        registry: TenantRegistry,
        routes: dict,
        budget: Any = None,
        builder: Optional[Callable] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.settings = settings
        self.registry = registry
        self.routes = routes  # the RestServer's live routing dict
        self.budget = budget
        self.builder = builder
        self._clock = clock
        self._lock = threading.RLock()
        self._states: dict[str, str] = {}  # guarded-by: _lock
        self._breakers: dict[str, CircuitBreaker] = {}  # guarded-by: _lock
        self._boundaries: dict[str, int] = {}  # round-close count  # guarded-by: _lock
        self._marks: dict[str, dict[str, float]] = {}  # counter marks  # guarded-by: _lock
        self._slo_paging: dict[str, set] = {}  # tenant -> paging SLOs  # guarded-by: _lock

    # -- state bookkeeping ---------------------------------------------------

    def _set_state_locked(self, tenant: str, state: str) -> None:
        self._states[tenant] = state  # lint: guarded-ok: _locked suffix — every caller holds _lock
        TENANT_STATE.labels(tenant=tenant).set(_STATE_VALUE[state])

    def state(self, tenant: str) -> str:
        with self._lock:
            return self._states.get(tenant, DRAINED)

    def states(self) -> dict[str, str]:
        with self._lock:
            return dict(self._states)

    def breaker(self, tenant: str) -> Optional[CircuitBreaker]:
        with self._lock:
            return self._breakers.get(tenant)

    def mark_serving(self, tenant: str) -> None:
        """Enter ``serving``: breaker + counter marks + configured
        weight/tier. The runner calls this for boot-time tenants; onboard
        calls it for runtime ones."""
        sched = get_scheduler()
        with self._lock:
            self._set_state_locked(tenant, SERVING)
            self._breakers.setdefault(
                tenant,
                CircuitBreaker(
                    component=f"tenant:{tenant}",
                    failure_threshold=self.settings.quarantine_failures,
                    reset_timeout_s=self.settings.quarantine_reset_s,
                    clock=self._clock,
                ),
            )
            self._marks[tenant] = self._sample_counters(tenant)
            weight = self.settings.tenant_weights().get(tenant)
            if weight is not None:
                sched.set_weight(tenant, weight)  # guarded-by: _lock
            tier = self.settings.tenant_tiers().get(tenant)
            if tier is not None:
                sched.set_tier(tenant, tier)  # guarded-by: _lock

    # -- admission (REST hot path) -------------------------------------------

    def admit(self, tenant: str) -> tuple[bool, Optional[float]]:
        """May ``tenant``'s mutating traffic (message POSTs, edge
        envelopes) be admitted right now? Returns ``(admit, retry_after_s)``.
        Read-only polls are always served — a draining tenant's in-flight
        round still needs its participants to fetch round params."""
        with self._lock:
            state = self._states.get(tenant)
            breaker = self._breakers.get(tenant)
        if state in (DRAINING, ONBOARDING):
            return False, None
        if state == QUARANTINED and breaker is not None:
            # breaker.state transitions open -> half-open by itself after
            # quarantine_reset_s: the first admit after that IS the probe
            if breaker.state == OPEN:
                return False, self.settings.quarantine_reset_s
        return True, None

    # -- round outcome feedback (phase close paths) --------------------------

    def note_round_completed(self, tenant: str) -> None:
        with self._lock:
            if tenant not in self._states:
                return
            self._boundaries[tenant] = self._boundaries.get(tenant, 0) + 1
            state = self._states.get(tenant)
            breaker = self._breakers.get(tenant)
        if breaker is None or state in (DRAINING, DRAINED):
            return
        if breaker.state == OPEN:
            # a degraded-close of pre-quarantine traffic while shedding:
            # not a probe outcome, must not end the quarantine early
            return
        breaker.record(True)
        if state == QUARANTINED:
            with self._lock:
                self._set_state_locked(tenant, SERVING)
                self._marks[tenant] = self._sample_counters(tenant)
            self._sync_demotion(tenant)
            logger.warning("tenant %s: probe round completed, quarantine lifted", tenant)

    def note_round_failed(self, tenant: str) -> None:
        with self._lock:
            if tenant not in self._states:
                return
            self._boundaries[tenant] = self._boundaries.get(tenant, 0) + 1
            state = self._states.get(tenant)
            breaker = self._breakers.get(tenant)
        if breaker is None or state in (DRAINING, DRAINED):
            return
        if breaker.state == OPEN:
            # self-inflicted: a shed tenant's rounds time out BECAUSE we
            # shed — recording them would hold the quarantine open forever
            return
        breaker.record(False)
        if breaker.state == OPEN and state != QUARANTINED:
            self._enter_quarantine(tenant)

    def _enter_quarantine(self, tenant: str) -> None:
        with self._lock:
            self._set_state_locked(tenant, QUARANTINED)
        TENANT_QUARANTINES.labels(tenant=tenant).inc()
        self._sync_demotion(tenant)
        deltas = self._counter_deltas(tenant)
        flight_dump(
            "tenant-quarantine",
            f"tenant {tenant} quarantined after "
            f"{self.settings.quarantine_failures} consecutive round failures",
            tenant=tenant,
            counter_deltas=scrub_attrs(deltas, "tenant-quarantine"),
        )
        logger.error(
            "tenant %s QUARANTINED (shedding with 429; half-open probe in %.0fs)",
            tenant,
            self.settings.quarantine_reset_s,
        )

    # -- SLO feedback (telemetry.slo transition hook) ------------------------

    def slo_transition(self, tenant: str, slo: str, severity: str) -> None:
        """Installed on the SLO engine: any SLO paging demotes the tenant's
        scheduler priority; recovery restores it. Fires on every severity
        change, both directions."""
        with self._lock:
            if tenant not in self._states:
                return
            paging = self._slo_paging.setdefault(tenant, set())
            if severity == "page":
                paging.add(slo)
            else:
                paging.discard(slo)
        self._sync_demotion(tenant)

    def _sync_demotion(self, tenant: str) -> None:
        """One writer for the scheduler demotion flag: demoted while
        quarantined OR while any SLO pages; restored when both clear."""
        with self._lock:
            demoted = self._states.get(tenant) == QUARANTINED or bool(
                self._slo_paging.get(tenant)
            )
            get_scheduler().set_demoted(tenant, demoted)  # guarded-by: _lock

    def install_slo_hook(self, engine) -> None:
        engine.set_transition_hook(self.slo_transition)

    # -- onboard -------------------------------------------------------------

    async def onboard(self, tenant: str) -> dict:
        """Build + admit a new tenant at runtime. Pool budget is allocated
        by the tenant's first leases against the configured caps; routes
        register only after the pipeline is fully up and the persisted
        kernel-calibration tier has been (re)loaded, so the tenant's first
        admitted round resolves its fold kernel from a warm verdict
        instead of racing inside its round wall."""
        validate_tenant_id(tenant)
        if self.builder is None:
            raise LifecycleError("runtime onboarding unavailable (no builder)")
        with self._lock:
            current = self._states.get(tenant, DRAINED)
            if current != DRAINED or self.registry.get(tenant) is not None:
                raise LifecycleError(f"tenant {tenant!r} is {current}, not drained")
            self._set_state_locked(tenant, ONBOARDING)
        t0 = self._clock()
        try:
            # warm step: refresh the disk calibration tier (a sibling
            # process — or this one's earlier cold onboard — may have
            # persisted verdicts since our last load)
            from ..utils import calibcache

            await asyncio.to_thread(calibcache.configure_from_env)
            ctx, troutes = await self.builder(tenant)
        except BaseException:
            with self._lock:
                self._states.pop(tenant, None)
                TENANT_STATE.labels(tenant=tenant).set(_STATE_VALUE[DRAINED])
            raise
        ctx.task = asyncio.create_task(ctx.machine.run(), name=f"machine-{tenant}")
        with self._lock:
            self.routes[tenant] = troutes  # guarded-by: _lock
            self.mark_serving(tenant)
        onboard_s = self._clock() - t0
        logger.info("tenant %s onboarded in %.3fs (serving)", tenant, onboard_s)
        return {"tenant": tenant, "state": SERVING, "onboard_s": round(onboard_s, 4)}

    # -- offboard ------------------------------------------------------------

    async def offboard(self, tenant: str) -> dict:
        """Graceful drain with hard-kill escalation. Admission stops the
        moment the state flips to ``draining``; the in-flight round then
        finishes or degraded-closes per the PR-5 stall-grace/quorum
        semantics (its already-admitted traffic keeps flowing, GET polls
        stay served). If no round boundary arrives inside
        ``drain_timeout_s``, the drain escalates: flight bundle, then the
        same hard teardown."""
        with self._lock:
            current = self._states.get(tenant, DRAINED)
            if current not in (SERVING, QUARANTINED):
                raise LifecycleError(f"tenant {tenant!r} is {current}, not drainable")
            self._set_state_locked(tenant, DRAINING)
            boundary0 = self._boundaries.get(tenant, 0)
        ctx = self.registry.get(tenant)
        deadline = self._clock() + self.settings.drain_timeout_s
        graceful = False
        while self._clock() < deadline:
            with self._lock:
                if self._boundaries.get(tenant, 0) > boundary0:
                    graceful = True
                    break
            if ctx is None or (ctx.task is not None and ctx.task.done()):
                graceful = True
                break
            await asyncio.sleep(0.05)
        outcome = "graceful" if graceful else "timeout"
        if not graceful:
            flight_dump(
                "tenant-drain-timeout",
                f"tenant {tenant} drain exceeded "
                f"{self.settings.drain_timeout_s:.0f}s; hard-killing",
                tenant=tenant,
                counter_deltas=scrub_attrs(
                    self._counter_deltas(tenant), "tenant-drain-timeout"
                ),
            )
            logger.error("tenant %s drain TIMED OUT; hard-kill escalation", tenant)
        TENANT_DRAINS.labels(outcome=outcome).inc()
        await self._teardown(tenant)
        with self._lock:
            self._set_state_locked(tenant, DRAINED)
            self._slo_paging.pop(tenant, None)
            self._breakers.pop(tenant, None)
            self._marks.pop(tenant, None)
        logger.info("tenant %s drained (%s)", tenant, outcome)
        return {"tenant": tenant, "state": DRAINED, "outcome": outcome}

    async def _teardown(self, tenant: str) -> None:
        """Hard teardown, shared by both drain outcomes: unroute,
        unregister, cancel the machine, close channels, stop the pipeline,
        then release every pool page and scheduler slot the tenant held."""
        with self._lock:
            self.routes.pop(tenant, None)  # guarded-by: _lock
            ctx = self.registry.remove(tenant)  # guarded-by: _lock
        if ctx is None:
            return
        if ctx.task is not None:
            ctx.task.cancel()
            try:
                await ctx.task
            except (asyncio.CancelledError, Exception):
                pass
        if ctx.request_tx is not None:
            ctx.request_tx.close()
        if ctx.pipeline is not None:
            await ctx.pipeline.stop()
        if ctx.metrics is not None:
            ctx.metrics.close()
        get_scheduler().forget_tenant(tenant)  # guarded-by: scheduler._cond
        if self.budget is not None:
            self.budget.discharge(tenant, self.budget.held(tenant))  # guarded-by: budget._lock
        # every buffer holder is dead (task cancelled, pipeline stopped):
        # collect the finalizer backstops, then force-release the rest —
        # zero leaked pages is the drain postcondition the churn soak pins
        await asyncio.to_thread(self._reclaim_pages, tenant)

    @staticmethod
    def _reclaim_pages(tenant: str) -> None:
        gc.collect()
        get_pool().reclaim(tenant)  # guarded-by: pool._lock

    # -- reconfigure ---------------------------------------------------------

    def reconfigure(self, tenant: str, weight: Optional[float] = None,
                    tier: Optional[int] = None) -> dict:
        """Runtime scheduling reconfiguration for a live tenant."""
        sched = get_scheduler()
        with self._lock:
            if self._states.get(tenant) not in (SERVING, QUARANTINED):
                raise LifecycleError(f"tenant {tenant!r} is not live")
            if weight is not None:
                sched.set_weight(tenant, float(weight))  # guarded-by: _lock
            if tier is not None:
                sched.set_tier(tenant, int(tier))  # guarded-by: _lock
        return {"tenant": tenant, "weight": weight, "tier": tier}

    # -- forensics -----------------------------------------------------------

    def _sample_counters(self, tenant: str) -> dict[str, float]:
        reg = get_registry()
        out: dict[str, float] = {}
        for family, extra, short in _DELTA_FAMILIES:
            value = reg.sample_value(family, {"tenant": tenant, **extra})
            out[short] = float(value or 0.0)
        return out

    def _counter_deltas(self, tenant: str) -> dict[str, float]:
        now = self._sample_counters(tenant)
        with self._lock:
            mark = self._marks.get(tenant, {})
        return {k: round(v - mark.get(k, 0.0), 3) for k, v in now.items()}


_manager_lock = threading.Lock()
_manager: Optional[TenantLifecycle] = None


def install_manager(manager: Optional[TenantLifecycle]) -> None:
    """Install the process lifecycle manager (multi-tenant runner startup;
    None uninstalls — single-tenant serving runs without one)."""
    global _manager
    with _manager_lock:
        _manager = manager


def get_manager() -> Optional[TenantLifecycle]:
    with _manager_lock:
        return _manager


def note_round_completed(tenant: str) -> None:
    """Phase-close forwarder (Unmask -> Idle). No-op without a manager;
    never raises — a lifecycle bug must not sink the round that just
    closed cleanly."""
    manager = get_manager()
    if manager is None:
        return
    try:
        manager.note_round_completed(tenant)
    except Exception:
        logger.exception("lifecycle round-completed hook failed")


def note_round_failed(tenant: str) -> None:
    """Phase-close forwarder (Failure -> Idle). No-op without a manager;
    never raises on the failure path it observes."""
    manager = get_manager()
    if manager is None:
        return
    try:
        manager.note_round_failed(tenant)
    except Exception:
        logger.exception("lifecycle round-failed hook failed")
