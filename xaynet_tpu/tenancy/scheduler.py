"""Tenant scheduler: fairness + backpressure over the shared fold pipeline.

NET-SA-style multi-stream aggregation (PAPERS.md) on one mesh: every
tenant's streaming pipeline asks this scheduler for a *fold-batch slot*
before dispatching a batch, and the scheduler grants slots

- **bounded** — at most ``max_inflight`` batches across ALL tenants are
  in flight at once (the mesh-wide backpressure: one tenant's burst
  cannot queue unbounded device work behind another tenant's fold), and
- **fairly** — when several tenants are waiting, the grant goes to the
  tenant with the fewest slots served so far (deficit round-robin,
  arrival order breaking ties), so a heavy tenant interleaves with a
  light one instead of starving it.

Slots are owned: each pipeline registers an owner id and every slot it
acquires is charged to that owner, so an abandoned pipeline (a round that
died mid-flight) returns its slots via ``release_owner`` — from the
pipeline's close() or its GC finalizer — instead of leaking scheduler
capacity for the life of the process.

The per-tenant served counters double as the round report's **fairness
split**: ``split()`` snapshots cumulative grants, ``window_split()``
drains the delta since the previous call (one round's interleave ratio).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..telemetry.registry import get_registry

_registry = get_registry()
TENANT_BATCHES = _registry.counter(
    "xaynet_tenant_fold_batches_total",
    "Fold-batch slots granted by the tenant scheduler, by tenant.",
    ("tenant",),
)
TENANT_SCHED_WAIT = _registry.counter(
    "xaynet_tenant_sched_wait_seconds_total",
    "Seconds producers spent waiting for a fold-batch slot, by tenant.",
    ("tenant",),
)
SCHED_INFLIGHT = _registry.gauge(
    "xaynet_tenant_sched_inflight",
    "Fold-batch slots currently granted across all tenants.",
)
SCHED_DEMOTIONS = _registry.counter(
    "xaynet_tenant_sched_demotions_total",
    "Preemptive demotions applied to a tenant by the SLO feedback loop "
    "(an over-budget tenant yields fold-batch slots until its burn "
    "recovers).",
    ("tenant",),
)

DEFAULT_MAX_INFLIGHT = 8


class TenantScheduler:
    """Fair, bounded fold-batch slot allocator (docs/DESIGN.md §19)."""

    def __init__(self, max_inflight: int = DEFAULT_MAX_INFLIGHT):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = max_inflight
        self._cond = threading.Condition()
        self._inflight = 0  # guarded-by: _cond
        self._owners: dict[int, int] = {}  # owner -> slots held  # guarded-by: _cond
        self._next_owner = 0  # guarded-by: _cond
        self._next_seq = 0  # guarded-by: _cond
        self._waiting: list[tuple[str, int]] = []  # (tenant, seq)  # guarded-by: _cond
        self._served: dict[str, int] = {}  # cumulative grants  # guarded-by: _cond
        self._window_prev: dict[str, int] = {}  # guarded-by: _cond
        self._weights: dict[str, float] = {}  # guarded-by: _cond
        self._tiers: dict[str, int] = {}  # guarded-by: _cond
        self._demoted: set[str] = set()  # guarded-by: _cond

    # -- ownership ----------------------------------------------------------

    def new_owner(self) -> int:
        with self._cond:
            self._next_owner += 1
            self._owners[self._next_owner] = 0
            return self._next_owner

    def release_owner(self, owner: int) -> None:
        """Return every slot the owner still holds (pipeline close / GC
        finalizer backstop). Idempotent."""
        with self._cond:
            held = self._owners.pop(owner, 0)
            if held:
                self._inflight -= held
                SCHED_INFLIGHT.dec(held)
                self._cond.notify_all()

    # -- slots --------------------------------------------------------------

    def _chosen(self) -> tuple[str, int]:
        """The waiter the next free slot belongs to, in precedence order:
        not SLO-demoted first (a demoted tenant only wins a slot when no
        healthy tenant is waiting — preemption at fold-batch granularity),
        then priority tier (lower tier number wins), then the smallest
        *weighted* deficit (served / weight: a weight-2 tenant earns slots
        twice as fast as a weight-1 one), FIFO on ties."""
        return min(
            self._waiting,
            key=lambda w: (
                w[0] in self._demoted,
                self._tiers.get(w[0], 0),
                self._served.get(w[0], 0) / self._weights.get(w[0], 1.0),
                w[1],
            ),
        )

    # -- SLO-weighted preemption -------------------------------------------

    def set_weight(self, tenant: str, weight: float) -> None:
        """Configure the tenant's fair-share weight (>= a weight-1 tenant's
        share per unit weight). Takes effect on the next grant decision."""
        if weight <= 0:
            raise ValueError("weight must be > 0")
        with self._cond:
            self._weights[tenant] = float(weight)
            self._cond.notify_all()

    def set_tier(self, tenant: str, tier: int) -> None:
        """Configure the tenant's priority tier (lower wins; default 0).
        A tier strictly dominates weights: tier-0 waiters always beat
        tier-1 waiters regardless of deficit."""
        with self._cond:
            self._tiers[tenant] = int(tier)
            self._cond.notify_all()

    def set_demoted(self, tenant: str, demoted: bool) -> None:
        """SLO feedback: an over-budget (burn-paging) tenant is demoted —
        it only receives fold-batch slots the healthy tenants do not
        want. Restoring is the same call with ``demoted=False``."""
        with self._cond:
            was = tenant in self._demoted
            if demoted:
                self._demoted.add(tenant)
            else:
                self._demoted.discard(tenant)
            changed = was != demoted
            if changed:
                self._cond.notify_all()
        if changed and demoted:
            SCHED_DEMOTIONS.labels(tenant=tenant).inc()

    def demoted(self) -> set[str]:
        with self._cond:
            return set(self._demoted)

    def forget_tenant(self, tenant: str) -> None:
        """Drop a drained tenant's scheduler state so a later re-onboard
        starts with a fresh deficit instead of a stale credit."""
        with self._cond:
            self._served.pop(tenant, None)
            self._window_prev.pop(tenant, None)
            self._weights.pop(tenant, None)
            self._tiers.pop(tenant, None)
            self._demoted.discard(tenant)
            self._cond.notify_all()

    def acquire(self, tenant: str, owner: int) -> None:
        """Block until a fold-batch slot is granted to ``tenant``."""
        t0 = time.monotonic()
        with self._cond:
            self._next_seq += 1
            me = (tenant, self._next_seq)
            self._waiting.append(me)
            try:
                while not (self._inflight < self.max_inflight and self._chosen() == me):
                    self._cond.wait()
            finally:
                self._waiting.remove(me)
            self._inflight += 1
            self._owners[owner] = self._owners.get(owner, 0) + 1
            self._served[tenant] = self._served.get(tenant, 0) + 1
            # another waiter may now be the chosen one for a remaining slot
            self._cond.notify_all()
        SCHED_INFLIGHT.inc()
        TENANT_BATCHES.labels(tenant=tenant).inc()
        waited = time.monotonic() - t0
        if waited > 0:
            TENANT_SCHED_WAIT.labels(tenant=tenant).inc(waited)

    def try_acquire_idle(self, tenant: str, owner: int) -> bool:
        """Grant a slot ONLY if the mesh is idle enough to give one away:
        capacity free AND no regular ``acquire`` waiter pending. Never
        blocks, never starves a real fold batch — the speculation/overlap
        engines (docs/DESIGN.md §22) use this to soak up scheduler slack
        between a round's fold batches. An idle grant is a normal owned
        slot (same ``release``/``release_owner``), but it is NOT charged
        to the fairness split: background speculation must not distort
        the deficit-round-robin ordering of real fold grants."""
        with self._cond:
            if self._inflight >= self.max_inflight or self._waiting:
                return False
            self._inflight += 1
            self._owners[owner] = self._owners.get(owner, 0) + 1
        SCHED_INFLIGHT.inc()
        TENANT_BATCHES.labels(tenant=tenant).inc()
        return True

    def release(self, owner: int) -> None:
        """Return one slot held by ``owner``."""
        with self._cond:
            held = self._owners.get(owner, 0)
            if held <= 0:
                return  # already returned via release_owner (idempotence)
            self._owners[owner] = held - 1
            self._inflight -= 1
            self._cond.notify_all()
        SCHED_INFLIGHT.dec()

    # -- fairness observability --------------------------------------------

    def split(self) -> dict[str, int]:
        """Cumulative fold-batch grants per tenant."""
        with self._cond:
            return dict(self._served)

    def window_split(self) -> dict[str, int]:
        """Grants per tenant since the previous ``window_split`` call (the
        round report's fairness section)."""
        with self._cond:
            out = {
                t: n - self._window_prev.get(t, 0)
                for t, n in self._served.items()
                if n - self._window_prev.get(t, 0) > 0
            }
            self._window_prev = dict(self._served)
            return out


_sched_lock = threading.Lock()
_scheduler: Optional[TenantScheduler] = None


def get_scheduler() -> TenantScheduler:
    """The process-wide tenant scheduler (configured from ``[tenancy]`` by
    the runner; the default bound keeps single-tenant pipelining intact)."""
    global _scheduler
    with _sched_lock:
        if _scheduler is None:
            _scheduler = TenantScheduler()
        return _scheduler


def configure_scheduler(max_inflight: int) -> TenantScheduler:
    global _scheduler
    sched = TenantScheduler(max_inflight=max_inflight)
    with _sched_lock:
        _scheduler = sched
    return sched
