"""Paged accumulator pool: fixed-size limb-plane pages under lease accounting.

The Ragged Paged Attention idiom (PAPERS.md) applied to aggregation
accumulators instead of KV cache: tenants' variable-length masked models
pack into one shared memory arena as runs of fixed-size pages, so many
models of different lengths coexist without per-tenant worst-case
reservations and without allocator fragmentation across rounds — a
released run coalesces back into the free list and the next tenant's
lease reuses the same physical pages.

Two arenas, one accounting discipline:

- **host arena** — real paging: a set of page-aligned uint8 slabs; a
  lease carves a *contiguous page run* out of a slab and hands back a
  typed numpy view. Contiguity per lease is the design point: every
  existing fold kernel (native strided C++, XLA, pallas) reads plain
  C-contiguous buffers, so paging lives at the allocator layer and the
  hot path is byte-identical to owning a private buffer. Leased memory is
  ZEROED before handoff — a page run previously owned by another tenant
  must never leak that tenant's masked bytes (the PR-14 secret-hygiene
  posture extended to memory reuse).
- **device arena** — a capacity ledger: device fold kernels donate their
  accumulators (`donate_argnums`), so a device buffer's identity is
  ephemeral by design and literal page views cannot survive a fold. What
  multi-tenant admission needs from HBM is the *budget*: the ledger
  tracks pages leased per tenant against the configured capacity and
  fails fast when a new tenant's plan would not fit.

Accounting invariant (checked at round boundaries and by the
``tenant-scope`` analysis pass's sanctioned-site whitelist): **leases ==
releases at round end** — every page run leased for a round's shard plan
and staging rings is released when the round's accumulator dies. The
clean path releases explicitly (`StagedAggregator.release_pool`, ring
close); `reclaim()` is the crash-path backstop the next round's Idle
phase runs, counting every straggler it had to force-release.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..telemetry.registry import get_registry

logger = logging.getLogger("xaynet.tenancy")

_registry = get_registry()
POOL_PAGES = _registry.gauge(
    "xaynet_pool_pages",
    "Pool pages currently leased, by arena (host | device) and tenant.",
    ("arena", "tenant"),
)
POOL_LEASES = _registry.counter(
    "xaynet_pool_leases_total",
    "Page-run leases granted, by arena and tenant.",
    ("arena", "tenant"),
)
POOL_RELEASES = _registry.counter(
    "xaynet_pool_releases_total",
    "Page-run leases released, by arena and tenant (reclaimed releases "
    "count here too).",
    ("arena", "tenant"),
)
POOL_RECLAIMED = _registry.counter(
    "xaynet_pool_reclaimed_total",
    "Leases force-released by the round-boundary reclaim (a crashed or "
    "abandoned round leaked them past its unmask release).",
    ("tenant",),
)
POOL_FRAGMENTATION = _registry.gauge(
    "xaynet_pool_fragmentation",
    "Host-arena fragmentation: 1 - largest free run / total free pages "
    "(0 when the free space is one contiguous run or the arena is full).",
)
POOL_COMPACTIONS = _registry.counter(
    "xaynet_pool_compactions_total",
    "Between-round host-arena compaction passes run by the Idle phase.",
)
POOL_PAGES_MIGRATED = _registry.counter(
    "xaynet_pool_pages_migrated_total",
    "Host pages moved by compaction (memmove under the lease lock, page "
    "tables rewritten atomically).",
)

DEFAULT_PAGE_BYTES = 1 << 20  # 1 MiB: a few limb-plane columns per page
DEFAULT_SLAB_PAGES = 64


class PoolExhausted(RuntimeError):
    """The arena's configured page capacity cannot satisfy the lease."""


@dataclass
class PageLease:
    """One granted page run. ``array`` is the typed view for host leases
    (None for device-ledger leases). Release is idempotent.

    ``migrator`` opts the lease into compaction: when set, ``compact()``
    may move the run to a lower offset and calls ``migrator(new_view)``
    so the holder swaps its reference. Migrators run under the pool lock
    and must be non-blocking reference swaps — holders register one only
    while their buffers are quiescent (between rounds). Leases without a
    migrator are immovable barriers."""

    tenant: str
    arena: str  # "host" | "device"
    lease_id: int
    pages: int
    slab: int = -1  # host: owning slab index
    offset: int = -1  # host: first page within the slab
    array: Optional[np.ndarray] = None
    released: bool = field(default=False, repr=False)
    migrator: Optional[object] = field(default=None, repr=False)


class _Slab:
    """One page-aligned host slab with a sorted free-run list."""

    def __init__(self, n_pages: int, page_bytes: int):
        self.n_pages = n_pages
        self.page_bytes = page_bytes
        self.buf = np.zeros(n_pages * page_bytes, dtype=np.uint8)
        self.free: list[tuple[int, int]] = [(0, n_pages)]  # (start, length)

    def take(self, pages: int) -> Optional[int]:
        """First-fit contiguous run; returns the start page or None."""
        for i, (start, length) in enumerate(self.free):
            if length >= pages:
                if length == pages:
                    del self.free[i]
                else:
                    self.free[i] = (start + pages, length - pages)
                return start
        return None

    def give(self, start: int, pages: int) -> None:
        """Return a run, coalescing with its neighbours."""
        runs = self.free
        runs.append((start, pages))
        runs.sort()
        merged: list[tuple[int, int]] = []
        for s, l in runs:
            if merged and merged[-1][0] + merged[-1][1] == s:
                merged[-1] = (merged[-1][0], merged[-1][1] + l)
            else:
                merged.append((s, l))
        self.free[:] = merged

    @property
    def free_pages(self) -> int:
        return sum(l for _, l in self.free)


class PagePool:
    """Host-slab page allocator + device capacity ledger with per-tenant
    page tables and lease/release accounting (docs/DESIGN.md §19)."""

    def __init__(
        self,
        page_bytes: int = DEFAULT_PAGE_BYTES,
        slab_pages: int = DEFAULT_SLAB_PAGES,
        host_pages: int = 0,
        device_pages: int = 0,
    ):
        if page_bytes < 4096 or page_bytes % 4096:
            raise ValueError("page_bytes must be a positive multiple of 4096")
        if slab_pages < 1:
            raise ValueError("slab_pages must be >= 1")
        self.page_bytes = page_bytes
        self.slab_pages = slab_pages
        # 0 = uncapped (the arena grows by slabs on demand); a cap makes
        # lease() raise PoolExhausted instead of over-committing
        self.host_pages = host_pages
        self.device_pages = device_pages
        self._lock = threading.Lock()
        self._slabs: list[_Slab] = []  # guarded-by: _lock
        self._leases: dict[int, PageLease] = {}  # guarded-by: _lock
        self._next_id = 0  # guarded-by: _lock
        self._in_use = {"host": 0, "device": 0}  # pages  # guarded-by: _lock

    # -- leasing ------------------------------------------------------------

    def pages_for(self, nbytes: int) -> int:
        return max(1, -(-int(nbytes) // self.page_bytes))

    def lease_host(self, tenant: str, shape: tuple, dtype) -> PageLease:
        """Lease a contiguous page run and return it as a ZEROED
        C-contiguous ``dtype[shape]`` view. Raises :class:`PoolExhausted`
        only when a configured ``host_pages`` cap cannot fit the run."""
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        pages = self.pages_for(nbytes)
        with self._lock:
            if self.host_pages and self._in_use["host"] + pages > self.host_pages:
                raise PoolExhausted(
                    f"host arena: {pages} pages requested, "
                    f"{self.host_pages - self._in_use['host']} of "
                    f"{self.host_pages} available"
                )
            slab_idx, start = -1, None
            for i, slab in enumerate(self._slabs):
                start = slab.take(pages)
                if start is not None:
                    slab_idx = i
                    break
            if start is None:
                # no run fits: grow the arena by one slab sized for the
                # request (large models get a dedicated slab; small ones
                # share the default slab granularity)
                slab = _Slab(max(self.slab_pages, pages), self.page_bytes)
                self._slabs.append(slab)
                slab_idx = len(self._slabs) - 1
                start = slab.take(pages)
            lease = self._grant_locked(tenant, "host", pages, slab_idx, start)
        raw = self._slabs[slab_idx].buf[
            start * self.page_bytes : start * self.page_bytes + nbytes
        ]
        view = raw.view(dtype).reshape(shape)
        view.fill(0)  # cross-tenant hygiene: never hand over another
        # tenant's masked bytes
        lease.array = view
        return lease

    def lease_device(self, tenant: str, nbytes: int) -> PageLease:
        """Ledger-only device lease: accounts ``nbytes`` of HBM as pages
        against the device capacity (device kernels donate buffers, so
        literal page views cannot survive a fold — DESIGN §19)."""
        pages = self.pages_for(nbytes)
        with self._lock:
            if self.device_pages and self._in_use["device"] + pages > self.device_pages:
                raise PoolExhausted(
                    f"device arena: {pages} pages requested, "
                    f"{self.device_pages - self._in_use['device']} of "
                    f"{self.device_pages} available"
                )
            return self._grant_locked(tenant, "device", pages, -1, -1)

    def _grant_locked(
        self, tenant: str, arena: str, pages: int, slab: int, offset: int
    ) -> PageLease:
        self._next_id += 1
        lease = PageLease(
            tenant=tenant,
            arena=arena,
            lease_id=self._next_id,
            pages=pages,
            slab=slab,
            offset=offset if offset is not None else -1,
        )
        self._leases[lease.lease_id] = lease
        self._in_use[arena] += pages
        POOL_PAGES.labels(arena=arena, tenant=tenant).inc(pages)
        POOL_LEASES.labels(arena=arena, tenant=tenant).inc()
        return lease

    def release(self, lease: PageLease) -> bool:
        """Return a lease's pages (idempotent: the GC finalizer backstop
        and the explicit unmask-path release may both run). Returns True
        only for the call that actually released — callers that account
        per-release (reclaim) key off this instead of assuming they won
        the race."""
        with self._lock:
            if lease.released or lease.lease_id not in self._leases:
                return False
            lease.released = True
            del self._leases[lease.lease_id]
            self._in_use[lease.arena] -= lease.pages
            if lease.arena == "host" and 0 <= lease.slab < len(self._slabs):
                self._slabs[lease.slab].give(lease.offset, lease.pages)
        lease.array = None
        lease.migrator = None
        POOL_PAGES.labels(arena=lease.arena, tenant=lease.tenant).dec(lease.pages)
        POOL_RELEASES.labels(arena=lease.arena, tenant=lease.tenant).inc()
        return True

    def set_migrator(self, lease: PageLease, migrator) -> None:
        """Register (or clear, with ``None``) a lease's compaction
        migrator ATOMICALLY with respect to :meth:`compact`: the toggle
        takes the lease lock, so a holder that clears the migrator before
        touching its buffer can never observe a half-migrated run — either
        a concurrent compaction already finished (``lease.array`` is the
        new view) or it will treat the lease as an immovable barrier.
        No-op on released leases."""
        with self._lock:
            if not lease.released:
                lease.migrator = migrator

    # -- accounting ---------------------------------------------------------

    def outstanding(self, tenant: Optional[str] = None) -> list[PageLease]:
        with self._lock:
            return [
                l
                for l in self._leases.values()
                if tenant is None or l.tenant == tenant
            ]

    def balanced(self, tenant: str) -> bool:
        """True when the tenant holds zero leases (the round-end invariant:
        every lease was released)."""
        return not self.outstanding(tenant)

    def reclaim(self, tenant: str) -> int:
        """Force-release every lease the tenant still holds — the
        round-boundary backstop for rounds that died before their unmask
        release. Returns the number reclaimed (0 on the healthy path).

        Idempotent per lease id: a GC finalizer may release a straggler
        between our ``outstanding()`` snapshot and the force-release, so
        only leases *this* call actually released count on
        ``xaynet_pool_reclaimed_total`` (counting the snapshot length
        double-counted those races)."""
        won = [lease for lease in self.outstanding(tenant) if self.release(lease)]
        if won:
            POOL_RECLAIMED.labels(tenant=tenant).inc(len(won))
            logger.warning(
                "pool: reclaimed %d leaked lease(s) (%d pages) from tenant %s",
                len(won),
                sum(l.pages for l in won),
                tenant,
            )
        return len(won)

    def page_table(self, tenant: str) -> dict[int, dict]:
        """The tenant's logical->physical mapping: lease id -> arena, slab,
        page offset, run length (host leases; device leases carry -1)."""
        with self._lock:
            return {
                l.lease_id: {
                    "arena": l.arena,
                    "slab": l.slab,
                    "offset": l.offset,
                    "pages": l.pages,
                }
                for l in self._leases.values()
                if l.tenant == tenant
            }

    def fragmentation(self) -> float:
        """Host-arena fragmentation in [0, 1): ``1 - largest free run /
        total free pages``. 0 means every free page is reachable as one
        contiguous run (or there is nothing free to fragment); values near
        1 mean the free space is shredded into runs too small to serve a
        large lease. Exported on ``xaynet_pool_fragmentation`` each call
        (the Idle phase samples it to decide whether to compact)."""
        with self._lock:
            frag = self._fragmentation_locked()
        POOL_FRAGMENTATION.set(frag)
        return frag

    def _fragmentation_locked(self) -> float:
        total = sum(s.free_pages for s in self._slabs)  # lint: guarded-ok: _locked suffix — every caller holds _lock
        if not total:
            return 0.0
        largest = max(
            (length for s in self._slabs for _, length in s.free),  # lint: guarded-ok: _locked suffix
            default=0,
        )
        return 1.0 - largest / total

    def compact(self) -> int:
        """Between-round host-arena compaction: slide migratable leases
        (those carrying a ``migrator``) toward page 0 of their slab so the
        free runs behind them coalesce, then drop fully-free trailing
        slabs. Returns the number of pages moved.

        The whole pass runs under the lease lock: bytes memmove to the new
        run, the page table (lease.slab/offset and the slab free lists) is
        rewritten atomically, and each holder's ``migrator(new_view)``
        swaps its reference before the lock drops — no thread can observe
        a half-migrated lease. Leases without a migrator (a round's live
        fold buffers) are immovable barriers; compaction never crosses
        them, so leases==releases accounting is untouched (no lease is
        released or granted here)."""
        moved_pages = 0
        with self._lock:
            by_slab: dict[int, list[PageLease]] = {}
            for lease in self._leases.values():
                if lease.arena == "host" and 0 <= lease.slab < len(self._slabs):
                    by_slab.setdefault(lease.slab, []).append(lease)
            for slab_idx, leases in by_slab.items():
                slab = self._slabs[slab_idx]
                cursor = 0
                for lease in sorted(leases, key=lambda l: l.offset):
                    if lease.migrator is None or lease.offset <= cursor:
                        # immovable barrier, or already packed: skip past it
                        cursor = max(cursor, lease.offset + lease.pages)
                        continue
                    src = lease.offset * self.page_bytes
                    dst = cursor * self.page_bytes
                    nbytes = (
                        lease.array.nbytes
                        if lease.array is not None
                        else lease.pages * self.page_bytes
                    )
                    # copy through a temp: src and dst runs may overlap
                    slab.buf[dst : dst + nbytes] = slab.buf[src : src + nbytes].copy()
                    moved_pages += lease.pages
                    lease.offset = cursor
                    if lease.array is not None:
                        raw = slab.buf[dst : dst + nbytes]
                        view = raw.view(lease.array.dtype).reshape(lease.array.shape)
                        lease.array = view
                        lease.migrator(view)
                    cursor += lease.pages
                # rewrite the free list as the complement of the (now
                # packed) occupied runs
                occupied = sorted(
                    (l.offset, l.pages)
                    for l in self._leases.values()
                    if l.arena == "host" and l.slab == slab_idx
                )
                free: list[tuple[int, int]] = []
                edge = 0
                for start, length in occupied:
                    if start > edge:
                        free.append((edge, start - edge))
                    edge = start + length
                if edge < slab.n_pages:
                    free.append((edge, slab.n_pages - edge))
                slab.free[:] = free
            # trim fully-free trailing slabs (mid-list slabs stay: lease
            # slab indices are positional)
            while self._slabs and self._slabs[-1].free_pages == self._slabs[-1].n_pages:
                self._slabs.pop()
            frag = self._fragmentation_locked()
        POOL_COMPACTIONS.inc()
        if moved_pages:
            POOL_PAGES_MIGRATED.inc(moved_pages)
            logger.info("pool: compaction migrated %d page(s)", moved_pages)
        POOL_FRAGMENTATION.set(frag)
        return moved_pages

    def stats(self) -> dict:
        with self._lock:
            tenant_leases: dict[str, int] = {}
            for lease in self._leases.values():
                tenant_leases[lease.tenant] = tenant_leases.get(lease.tenant, 0) + 1
            return {
                "page_bytes": self.page_bytes,
                "slabs": len(self._slabs),
                "host_pages_in_use": self._in_use["host"],
                "host_pages_free": sum(s.free_pages for s in self._slabs),
                "device_pages_in_use": self._in_use["device"],
                "leases": len(self._leases),
                "tenant_leases": tenant_leases,
                "fragmentation": self._fragmentation_locked(),
            }


_pool_lock = threading.Lock()
_pool: Optional[PagePool] = None


def get_pool() -> PagePool:
    """The process-wide accumulator pool (configured from ``[tenancy]`` by
    the runner; defaults are fine for tests and single-tenant use)."""
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = PagePool()
        return _pool


def configure_pool(
    page_kib: int, slab_pages: int, host_pages: int, device_pages: int
) -> PagePool:
    """Install the configured process pool (runner startup). Replaces the
    default instance; existing leases on the old pool keep their slabs
    alive through their own references."""
    global _pool
    pool = PagePool(
        page_bytes=page_kib * 1024,
        slab_pages=slab_pages,
        host_pages=host_pages,
        device_pages=device_pages,
    )
    with _pool_lock:
        _pool = pool
    return pool
