"""Paged accumulator pool: fixed-size limb-plane pages under lease accounting.

The Ragged Paged Attention idiom (PAPERS.md) applied to aggregation
accumulators instead of KV cache: tenants' variable-length masked models
pack into one shared memory arena as runs of fixed-size pages, so many
models of different lengths coexist without per-tenant worst-case
reservations and without allocator fragmentation across rounds — a
released run coalesces back into the free list and the next tenant's
lease reuses the same physical pages.

Two arenas, one accounting discipline:

- **host arena** — real paging: a set of page-aligned uint8 slabs; a
  lease carves a *contiguous page run* out of a slab and hands back a
  typed numpy view. Contiguity per lease is the design point: every
  existing fold kernel (native strided C++, XLA, pallas) reads plain
  C-contiguous buffers, so paging lives at the allocator layer and the
  hot path is byte-identical to owning a private buffer. Leased memory is
  ZEROED before handoff — a page run previously owned by another tenant
  must never leak that tenant's masked bytes (the PR-14 secret-hygiene
  posture extended to memory reuse).
- **device arena** — a capacity ledger: device fold kernels donate their
  accumulators (`donate_argnums`), so a device buffer's identity is
  ephemeral by design and literal page views cannot survive a fold. What
  multi-tenant admission needs from HBM is the *budget*: the ledger
  tracks pages leased per tenant against the configured capacity and
  fails fast when a new tenant's plan would not fit.

Accounting invariant (checked at round boundaries and by the
``tenant-scope`` analysis pass's sanctioned-site whitelist): **leases ==
releases at round end** — every page run leased for a round's shard plan
and staging rings is released when the round's accumulator dies. The
clean path releases explicitly (`StagedAggregator.release_pool`, ring
close); `reclaim()` is the crash-path backstop the next round's Idle
phase runs, counting every straggler it had to force-release.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..telemetry.registry import get_registry

logger = logging.getLogger("xaynet.tenancy")

_registry = get_registry()
POOL_PAGES = _registry.gauge(
    "xaynet_pool_pages",
    "Pool pages currently leased, by arena (host | device) and tenant.",
    ("arena", "tenant"),
)
POOL_LEASES = _registry.counter(
    "xaynet_pool_leases_total",
    "Page-run leases granted, by arena and tenant.",
    ("arena", "tenant"),
)
POOL_RELEASES = _registry.counter(
    "xaynet_pool_releases_total",
    "Page-run leases released, by arena and tenant (reclaimed releases "
    "count here too).",
    ("arena", "tenant"),
)
POOL_RECLAIMED = _registry.counter(
    "xaynet_pool_reclaimed_total",
    "Leases force-released by the round-boundary reclaim (a crashed or "
    "abandoned round leaked them past its unmask release).",
    ("tenant",),
)

DEFAULT_PAGE_BYTES = 1 << 20  # 1 MiB: a few limb-plane columns per page
DEFAULT_SLAB_PAGES = 64


class PoolExhausted(RuntimeError):
    """The arena's configured page capacity cannot satisfy the lease."""


@dataclass
class PageLease:
    """One granted page run. ``array`` is the typed view for host leases
    (None for device-ledger leases). Release is idempotent."""

    tenant: str
    arena: str  # "host" | "device"
    lease_id: int
    pages: int
    slab: int = -1  # host: owning slab index
    offset: int = -1  # host: first page within the slab
    array: Optional[np.ndarray] = None
    released: bool = field(default=False, repr=False)


class _Slab:
    """One page-aligned host slab with a sorted free-run list."""

    def __init__(self, n_pages: int, page_bytes: int):
        self.n_pages = n_pages
        self.page_bytes = page_bytes
        self.buf = np.zeros(n_pages * page_bytes, dtype=np.uint8)
        self.free: list[tuple[int, int]] = [(0, n_pages)]  # (start, length)

    def take(self, pages: int) -> Optional[int]:
        """First-fit contiguous run; returns the start page or None."""
        for i, (start, length) in enumerate(self.free):
            if length >= pages:
                if length == pages:
                    del self.free[i]
                else:
                    self.free[i] = (start + pages, length - pages)
                return start
        return None

    def give(self, start: int, pages: int) -> None:
        """Return a run, coalescing with its neighbours."""
        runs = self.free
        runs.append((start, pages))
        runs.sort()
        merged: list[tuple[int, int]] = []
        for s, l in runs:
            if merged and merged[-1][0] + merged[-1][1] == s:
                merged[-1] = (merged[-1][0], merged[-1][1] + l)
            else:
                merged.append((s, l))
        self.free[:] = merged

    @property
    def free_pages(self) -> int:
        return sum(l for _, l in self.free)


class PagePool:
    """Host-slab page allocator + device capacity ledger with per-tenant
    page tables and lease/release accounting (docs/DESIGN.md §19)."""

    def __init__(
        self,
        page_bytes: int = DEFAULT_PAGE_BYTES,
        slab_pages: int = DEFAULT_SLAB_PAGES,
        host_pages: int = 0,
        device_pages: int = 0,
    ):
        if page_bytes < 4096 or page_bytes % 4096:
            raise ValueError("page_bytes must be a positive multiple of 4096")
        if slab_pages < 1:
            raise ValueError("slab_pages must be >= 1")
        self.page_bytes = page_bytes
        self.slab_pages = slab_pages
        # 0 = uncapped (the arena grows by slabs on demand); a cap makes
        # lease() raise PoolExhausted instead of over-committing
        self.host_pages = host_pages
        self.device_pages = device_pages
        self._lock = threading.Lock()
        self._slabs: list[_Slab] = []  # guarded-by: _lock
        self._leases: dict[int, PageLease] = {}  # guarded-by: _lock
        self._next_id = 0  # guarded-by: _lock
        self._in_use = {"host": 0, "device": 0}  # pages  # guarded-by: _lock

    # -- leasing ------------------------------------------------------------

    def pages_for(self, nbytes: int) -> int:
        return max(1, -(-int(nbytes) // self.page_bytes))

    def lease_host(self, tenant: str, shape: tuple, dtype) -> PageLease:
        """Lease a contiguous page run and return it as a ZEROED
        C-contiguous ``dtype[shape]`` view. Raises :class:`PoolExhausted`
        only when a configured ``host_pages`` cap cannot fit the run."""
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        pages = self.pages_for(nbytes)
        with self._lock:
            if self.host_pages and self._in_use["host"] + pages > self.host_pages:
                raise PoolExhausted(
                    f"host arena: {pages} pages requested, "
                    f"{self.host_pages - self._in_use['host']} of "
                    f"{self.host_pages} available"
                )
            slab_idx, start = -1, None
            for i, slab in enumerate(self._slabs):
                start = slab.take(pages)
                if start is not None:
                    slab_idx = i
                    break
            if start is None:
                # no run fits: grow the arena by one slab sized for the
                # request (large models get a dedicated slab; small ones
                # share the default slab granularity)
                slab = _Slab(max(self.slab_pages, pages), self.page_bytes)
                self._slabs.append(slab)
                slab_idx = len(self._slabs) - 1
                start = slab.take(pages)
            lease = self._grant_locked(tenant, "host", pages, slab_idx, start)
        raw = self._slabs[slab_idx].buf[
            start * self.page_bytes : start * self.page_bytes + nbytes
        ]
        view = raw.view(dtype).reshape(shape)
        view.fill(0)  # cross-tenant hygiene: never hand over another
        # tenant's masked bytes
        lease.array = view
        return lease

    def lease_device(self, tenant: str, nbytes: int) -> PageLease:
        """Ledger-only device lease: accounts ``nbytes`` of HBM as pages
        against the device capacity (device kernels donate buffers, so
        literal page views cannot survive a fold — DESIGN §19)."""
        pages = self.pages_for(nbytes)
        with self._lock:
            if self.device_pages and self._in_use["device"] + pages > self.device_pages:
                raise PoolExhausted(
                    f"device arena: {pages} pages requested, "
                    f"{self.device_pages - self._in_use['device']} of "
                    f"{self.device_pages} available"
                )
            return self._grant_locked(tenant, "device", pages, -1, -1)

    def _grant_locked(
        self, tenant: str, arena: str, pages: int, slab: int, offset: int
    ) -> PageLease:
        self._next_id += 1
        lease = PageLease(
            tenant=tenant,
            arena=arena,
            lease_id=self._next_id,
            pages=pages,
            slab=slab,
            offset=offset if offset is not None else -1,
        )
        self._leases[lease.lease_id] = lease
        self._in_use[arena] += pages
        POOL_PAGES.labels(arena=arena, tenant=tenant).inc(pages)
        POOL_LEASES.labels(arena=arena, tenant=tenant).inc()
        return lease

    def release(self, lease: PageLease) -> None:
        """Return a lease's pages (idempotent: the GC finalizer backstop
        and the explicit unmask-path release may both run)."""
        with self._lock:
            if lease.released or lease.lease_id not in self._leases:
                return
            lease.released = True
            del self._leases[lease.lease_id]
            self._in_use[lease.arena] -= lease.pages
            if lease.arena == "host" and 0 <= lease.slab < len(self._slabs):
                self._slabs[lease.slab].give(lease.offset, lease.pages)
        lease.array = None
        POOL_PAGES.labels(arena=lease.arena, tenant=lease.tenant).dec(lease.pages)
        POOL_RELEASES.labels(arena=lease.arena, tenant=lease.tenant).inc()

    # -- accounting ---------------------------------------------------------

    def outstanding(self, tenant: Optional[str] = None) -> list[PageLease]:
        with self._lock:
            return [
                l
                for l in self._leases.values()
                if tenant is None or l.tenant == tenant
            ]

    def balanced(self, tenant: str) -> bool:
        """True when the tenant holds zero leases (the round-end invariant:
        every lease was released)."""
        return not self.outstanding(tenant)

    def reclaim(self, tenant: str) -> int:
        """Force-release every lease the tenant still holds — the
        round-boundary backstop for rounds that died before their unmask
        release. Returns the number reclaimed (0 on the healthy path)."""
        stale = self.outstanding(tenant)
        for lease in stale:
            self.release(lease)
        if stale:
            POOL_RECLAIMED.labels(tenant=tenant).inc(len(stale))
            logger.warning(
                "pool: reclaimed %d leaked lease(s) (%d pages) from tenant %s",
                len(stale),
                sum(l.pages for l in stale),
                tenant,
            )
        return len(stale)

    def page_table(self, tenant: str) -> dict[int, dict]:
        """The tenant's logical->physical mapping: lease id -> arena, slab,
        page offset, run length (host leases; device leases carry -1)."""
        with self._lock:
            return {
                l.lease_id: {
                    "arena": l.arena,
                    "slab": l.slab,
                    "offset": l.offset,
                    "pages": l.pages,
                }
                for l in self._leases.values()
                if l.tenant == tenant
            }

    def stats(self) -> dict:
        with self._lock:
            return {
                "page_bytes": self.page_bytes,
                "slabs": len(self._slabs),
                "host_pages_in_use": self._in_use["host"],
                "host_pages_free": sum(s.free_pages for s in self._slabs),
                "device_pages_in_use": self._in_use["device"],
                "leases": len(self._leases),
            }


_pool_lock = threading.Lock()
_pool: Optional[PagePool] = None


def get_pool() -> PagePool:
    """The process-wide accumulator pool (configured from ``[tenancy]`` by
    the runner; defaults are fine for tests and single-tenant use)."""
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = PagePool()
        return _pool


def configure_pool(
    page_kib: int, slab_pages: int, host_pages: int, device_pages: int
) -> PagePool:
    """Install the configured process pool (runner startup). Replaces the
    default instance; existing leases on the old pool keep their slabs
    alive through their own references."""
    global _pool
    pool = PagePool(
        page_bytes=page_kib * 1024,
        slab_pages=slab_pages,
        host_pages=host_pages,
        device_pages=device_pages,
    )
    with _pool_lock:
        _pool = pool
    return pool
